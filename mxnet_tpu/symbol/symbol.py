"""Symbol: declarative graph composition.

TPU-native re-design of the reference's nnvm Symbol world
(``3rdparty/tvm/nnvm :: nnvm::Graph/Node``, ``python/mxnet/symbol/
symbol.py``).  A Symbol is a DAG of op nodes over the SAME op registry as
``mx.nd`` -- execution is a topological walk of pure JAX calls, jitted by
the Executor (the XLA answer to GraphExecutor+PlanMemory: buffer
assignment and fusion come from the compiler).

Serialization keeps the reference's ``-symbol.json`` schema (``nodes`` /
``arg_nodes`` / ``heads``) so exported models interoperate.
"""
from __future__ import annotations

import ast
import json

import numpy as np

from ..base import MXNetError, _NameManager
from ..ops.registry import OP_REGISTRY, get_op

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "_eval_symbol"]


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs")

    def __init__(self, op, name, attrs, inputs, num_outputs=1):
        self.op = op            # op name string, or None for variable
        self.name = name
        self.attrs = attrs      # dict[str, str-able]
        self.inputs = inputs    # list[(Node, out_index)]
        self.num_outputs = num_outputs


class Symbol:
    """One or more output entries of a graph (reference: ``Symbol``)."""

    def __init__(self, outputs):
        self._outputs = outputs  # list[(Node, out_index)]

    # -- composition ---------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        return "<Symbol %s>" % (self.name or "group[%d]" % len(self._outputs))

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("output %r not found" % index)
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (self[i] for i in range(len(self._outputs)))

    def _binop(self, other, opname, reverse=False):
        if isinstance(other, Symbol):
            lhs, rhs = (other, self) if reverse else (self, other)
            return _make_node(opname, [lhs, rhs], {})
        scalar_map = {"elemwise_add": "_plus_scalar",
                      "elemwise_sub": "_rminus_scalar" if reverse else "_minus_scalar",
                      "elemwise_mul": "_mul_scalar",
                      "elemwise_div": "_rdiv_scalar" if reverse else "_div_scalar",
                      "broadcast_power": "_rpower_scalar" if reverse else "_power_scalar"}
        return _make_node(scalar_map[opname], [self], {"scalar": float(other)})

    def __add__(self, o):
        return self._binop(o, "elemwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div")

    def __rtruediv__(self, o):
        return self._binop(o, "elemwise_div", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power")

    def __neg__(self):
        return _make_node("negative", [self], {})

    # -- graph queries -------------------------------------------------
    def _topo(self):
        # Iterative DFS: graph depth is unbounded (deep sequential models),
        # so recursion would hit the Python stack limit.
        order = []
        seen = set()
        for root, _ in self._outputs:
            if id(root) in seen:
                continue
            stack = [(root, False)]
            while stack:
                node, expanded = stack.pop()
                if expanded:
                    order.append(node)
                    continue
                if id(node) in seen:
                    continue
                seen.add(id(node))
                stack.append((node, True))
                for inp, _ in reversed(node.inputs):
                    if id(inp) not in seen:
                        stack.append((inp, False))
        return order

    def list_arguments(self):
        """Variable names in topo order (reference: ``list_arguments``).
        Aux-state variables (``__aux__`` attr, e.g. BatchNorm running
        stats) are excluded, as in the reference."""
        return [n.name for n in self._topo()
                if n.op is None and "__aux__" not in n.attrs]

    def list_outputs(self):
        out = []
        for node, idx in self._outputs:
            if node.num_outputs > 1:
                out.append("%s_output%d" % (node.name, idx))
            else:
                out.append(node.name + "_output")
        return out

    def list_auxiliary_states(self):
        """Aux-state variable names (reference:
        ``list_auxiliary_states``): mutable non-gradient inputs such as
        BatchNorm moving_mean/moving_var."""
        return [n.name for n in self._topo()
                if n.op is None and "__aux__" in n.attrs]

    def get_internals(self):
        nodes = self._topo()
        return Symbol([(n, i) for n in nodes for i in range(n.num_outputs)])

    def attr(self, key):
        return self._outputs[0][0].attrs.get(key)

    # -- shape/type inference -----------------------------------------
    def infer_shape(self, **kwargs):
        """Reference: ``infer_shape`` (nnvm InferShape pass).

        Forward abstract interpretation: each node is shape-propagated
        with ``jax.eval_shape``; parameter variables whose shapes are not
        given are deduced by per-op rules (the analog of each op's
        FInferShape), so passing only data/label shapes is enough --
        exactly the contract ``Module.bind`` relies on.
        """
        return _infer_shapes_forward(self, kwargs, partial=False)

    def infer_shape_partial(self, **kwargs):
        """Like ``infer_shape`` but returns ``None`` for undeducible
        arguments instead of raising (reference:
        ``infer_shape_partial``)."""
        return _infer_shapes_forward(self, kwargs, partial=True)

    def infer_type(self, **kwargs):
        arg_names = self.list_arguments()
        return ([np.float32] * len(arg_names),
                [np.float32] * len(self._outputs), [])

    # -- execution -----------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        from ..ndarray import NDArray
        feed = {k: v for k, v in kwargs.items()}
        outs = _eval_symbol(self, feed)
        return outs

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, check=None, **kwargs):
        from ..executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req,
                        aux_states=aux_states, group2ctx=group2ctx,
                        check=check)

    def simple_bind(self, ctx=None, grad_req="write", check=None, **shapes):
        """Allocate all arguments and bind (reference: ``simple_bind``).
        Parameter shapes not passed explicitly are inferred from the
        data/label shapes via ``infer_shape``.  ``check=True`` (or
        ``MXNET_TPU_GRAPH_CHECK=1``) runs the static graph checker
        (``mxnet_tpu.analysis``) before binding."""
        from ..executor import Executor
        from ..ndarray import zeros
        arg_names = self.list_arguments()
        arg_shapes, _, aux_shapes = self.infer_shape(**shapes)
        args = {name: zeros(shape, ctx=ctx)
                for name, shape in zip(arg_names, arg_shapes)}
        args_grad = {k: zeros(v.shape, ctx=ctx) for k, v in args.items()} \
            if grad_req != "null" else None
        aux = {name: zeros(shape, ctx=ctx)
               for name, shape in zip(self.list_auxiliary_states(),
                                      aux_shapes)}
        return Executor(self, ctx, args, args_grad, grad_req,
                        aux_states=aux, check=check)

    # -- serialization (reference: nnvm saveload_json.cc) -------------
    def tojson(self):
        nodes = self._topo()
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": n.op if n.op is not None else "null",
                "name": n.name,
                "attrs": {k: str(v) for k, v in n.attrs.items()},
                "inputs": [[node_ids[id(src)], oi, 0] for src, oi in n.inputs],
            })
        heads = [[node_ids[id(n)], oi, 0] for n, oi in self._outputs]
        arg_nodes = [i for i, n in enumerate(nodes) if n.op is None]
        return json.dumps({
            "nodes": jnodes, "arg_nodes": arg_nodes, "heads": heads,
            "attrs": {"mxnet_version": ["int", 10700],
                      "mxnet_tpu": ["str", "1"]},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())


def var(name, shape=None, dtype=None, **kwargs):
    """Create a variable symbol (reference: ``symbol.var``).  Picks up
    any enclosing AttrScope attributes, as op nodes do."""
    from ..attribute import AttrScope
    attrs = AttrScope.current_attrs()
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    attrs.update({k: str(v) for k, v in kwargs.items()})
    return Symbol([(_Node(None, name, attrs, []), 0)])


Variable = var


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def _parse_attr_value(v):
    # Attrs loaded from -symbol.json are untrusted; literal_eval covers the
    # tuples/numbers/bools they contain without an eval() code-exec surface
    # (the reference parses attrs with typed dmlc parameter parsing).
    s = str(v)
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


# Ops whose extra outputs are secondary (stats, states): composing the
# whole symbol as an input means "take the primary output", matching the
# reference's visible-output convention for these ops.
_PRIMARY_FIRST = {"BatchNorm", "RNN"}

# Aux-state arguments (reference: mutable inputs / aux states): maps the
# arg name to the output index that carries its updated value, so
# executors can write running stats back after a training forward.
_AUX_ARGS = {"BatchNorm": {"moving_mean": 1, "moving_var": 2}}


def _skip_auto_var(opname, params, arg_name):
    """True when a missing tensor arg must NOT be auto-created (it is
    structurally absent, not an implicit parameter)."""
    if arg_name == "bias" and params.get("no_bias"):
        return True
    if opname == "RNN" and arg_name == "state_cell" \
            and params.get("mode", "lstm") != "lstm":
        return True
    return False


def _make_node(opname, input_syms, params, name=None):
    op = get_op(opname)
    hint = opname.lower().lstrip("_")
    name = _NameManager.current().get(name, hint)
    inputs = []
    for s in input_syms:
        if not isinstance(s, Symbol):
            raise MXNetError("op %s: expected Symbol input, got %r"
                             % (opname, s))
        if len(s._outputs) != 1:
            if s._outputs[0][0].op in _PRIMARY_FIRST:
                inputs.append(s._outputs[0])
                continue
            raise MXNetError("op %s: cannot take group symbol" % opname)
        inputs.append(s._outputs[0])
    from ..attribute import AttrScope
    # Auto-create variables for omitted tensor args (reference: nnvm
    # composition creates "{name}_{arg}" vars for missing inputs) so
    # Module users write `sym.FullyConnected(data, num_hidden=k)` and get
    # fc_weight/fc_bias arguments implicitly.
    if not op.variadic and len(inputs) < len(op.arg_names):
        aux_map = _AUX_ARGS.get(opname, {})
        scope_attrs = AttrScope.current_attrs()
        for arg_name in op.arg_names[len(inputs):]:
            if _skip_auto_var(opname, params, arg_name):
                continue
            attrs = dict(scope_attrs)
            if arg_name in aux_map:
                attrs["__aux__"] = "1"
            vnode = _Node(None, "%s_%s" % (name, arg_name), attrs, [])
            inputs.append((vnode, 0))
    # count outputs via an abstract probe later; store param attrs now,
    # under any enclosing AttrScope attributes (reference: AttrScope
    # attaches e.g. ctx_group to every symbol made in the scope)
    attrs = AttrScope.current_attrs()
    attrs.update(params)
    node = _Node(opname, name, attrs, inputs)
    node.num_outputs = _probe_num_outputs(op, node)
    return Symbol([(node, i) for i in range(node.num_outputs)]) \
        if node.num_outputs > 1 else Symbol([(node, 0)])


def _probe_num_outputs(op, node):
    # cheap static probes for known multi-output ops
    if op.name == "split" or op.name == "SliceChannel":
        return int(node.attrs.get("num_outputs", 1))
    if op.name == "BatchNorm":
        return 3
    if op.name == "RNN":
        return 3 if node.attrs.get("mode", "lstm") == "lstm" else 2
    if op.name == "topk":
        return 2 if node.attrs.get("ret_typ") == "both" else 1
    if op.name in ("linalg_syevd", "linalg_slogdet", "moments"):
        return 2
    if op.name == "linalg_svd":
        return 3
    if op.name in ("quantize", "quantize_v2", "requantize",
                   "quantized_fully_connected", "quantized_conv",
                   "quantized_pooling"):
        return 3
    return 1


def _node_params(node, op):
    params = op.param_defaults()
    for k, v in node.attrs.items():
        if k.startswith("__"):
            continue
        if any(p.name == k for p in op.params):
            params[k] = _parse_attr_value(v)
    return params


def _eval_node_value(node, values, op_params_override=None):
    """Evaluate one node given input values."""
    from .. import random as _random_mod
    op = get_op(node.op)
    params = _node_params(node, op)
    args = [values[(id(src), oi)] for src, oi in node.inputs]
    if not op.variadic and len(args) < len(op.arg_names):
        # optional trailing tensor inputs (e.g. bias with no_bias=True)
        args = args + [None] * (len(op.arg_names) - len(args))
    fn = op.fcompute
    if op.stateful_rng:
        import functools
        fn = functools.partial(fn, _random_mod.next_key())
    from .. import autograd
    if any(p.name == "training" for p in op.params) and \
            "training" not in node.attrs:
        params["training"] = autograd.is_training()
    return fn(*args, **params)


# ----------------------------------------------------------------------
# Forward shape inference (nnvm InferShape analog)
# ----------------------------------------------------------------------

def _as_tuple(v):
    return (v,) if isinstance(v, int) else tuple(v)


def _param_shape_rule(opname, params, arg_name, in_shapes):
    """Deduce the shape of parameter variable ``arg_name`` of op
    ``opname`` from the (known) data input shape -- the per-op FInferShape
    half the Module path needs.  ``in_shapes[0]`` is the data shape.
    Returns a shape tuple or None if no rule applies."""
    data = in_shapes[0] if in_shapes and in_shapes[0] is not None else None
    if data is None:
        return None
    if opname == "FullyConnected":
        nh = int(params.get("num_hidden", 0))
        if arg_name == "weight":
            k = int(np.prod(data[1:])) if params.get("flatten", True) \
                else int(data[-1])
            return (nh, k)
        if arg_name == "bias":
            return (nh,)
    elif opname == "Convolution":
        nf = int(params.get("num_filter", 0))
        kernel = _as_tuple(params.get("kernel", ()))
        groups = int(params.get("num_group", 1))
        if arg_name == "weight":
            return (nf, int(data[1]) // groups) + kernel
        if arg_name == "bias":
            return (nf,)
    elif opname == "Deconvolution":
        nf = int(params.get("num_filter", 0))
        kernel = _as_tuple(params.get("kernel", ()))
        if arg_name == "weight":
            return (int(data[1]), nf) + kernel
        if arg_name == "bias":
            return (nf,)
    elif opname in ("BatchNorm", "InstanceNorm", "GroupNorm"):
        axis = int(params.get("axis", 1))
        return (int(data[axis]),)
    elif opname == "LayerNorm":
        axis = int(params.get("axis", -1))
        return (int(data[axis]),)
    elif opname == "Embedding":
        return (int(params.get("input_dim", 0)),
                int(params.get("output_dim", 0)))
    elif opname == "_prelu":
        return (int(data[1]),) if len(data) > 1 else (1,)
    elif opname in ("SoftmaxOutput", "LogisticRegressionOutput"):
        if arg_name == "label":
            return (int(data[0]),)
    elif opname in ("LinearRegressionOutput", "MAERegressionOutput",
                    "softmax_cross_entropy"):
        if arg_name == "label":
            return tuple(data)
    return None


def _infer_shapes_forward(sym, known, partial=False):
    """Walk the graph forward, shape-propagating each node with
    ``jax.eval_shape`` and deducing unknown parameter-variable shapes
    with `_param_shape_rule`.  Returns (arg_shapes, out_shapes) in
    ``list_arguments()`` / ``list_outputs()`` order."""
    import functools
    import jax

    known = {k: tuple(v) for k, v in known.items()}
    var_shape = {}          # name -> tuple
    specs = {}              # (id(node), oi) -> ShapeDtypeStruct

    def var_spec(node):
        name = node.name
        if name in known:
            shape = known[name]
        elif "__shape__" in node.attrs:
            shape = tuple(_parse_attr_value(node.attrs["__shape__"]))
        else:
            return None
        var_shape[name] = shape
        dt = node.attrs.get("__dtype__", "float32")
        return jax.ShapeDtypeStruct(shape, np.dtype(str(dt)))

    for node in sym._topo():
        if node.op is None:
            s = var_spec(node)
            if s is not None:
                specs[(id(node), 0)] = s
            continue
        op = get_op(node.op)
        params = _node_params(node, op)
        in_specs = []
        in_shapes = [specs.get((id(src), oi)) for src, oi in node.inputs]
        in_shapes = [tuple(s.shape) if s is not None else None
                     for s in in_shapes]
        unresolved = False
        for i, (src, oi) in enumerate(node.inputs):
            s = specs.get((id(src), oi))
            if s is None and src.op is None:
                shape = _param_shape_rule(node.op, params,
                                          op.arg_names[i] if i < len(op.arg_names) else "",
                                          in_shapes)
                if shape is not None:
                    s = jax.ShapeDtypeStruct(shape, np.float32)
                    specs[(id(src), oi)] = s
                    var_shape[src.name] = shape
            if s is None:
                unresolved = True
            in_specs.append(s)
        if unresolved:
            if partial:
                continue
            missing = [src.name for (src, oi), s
                       in zip(node.inputs, in_specs) if s is None]
            raise MXNetError(
                "infer_shape: cannot deduce shape(s) of %r feeding op "
                "%s(%s); pass them explicitly" %
                (missing, node.op, node.name))
        nargs = len(in_specs)
        if not op.variadic and nargs < len(op.arg_names):
            pad = len(op.arg_names) - nargs
        else:
            pad = 0

        fn = op.fcompute
        if op.stateful_rng:
            fn = functools.partial(fn, jax.random.PRNGKey(0))
        if any(p.name == "training" for p in op.params) and \
                "training" not in node.attrs:
            params["training"] = False
        try:
            out = jax.eval_shape(
                lambda *a: fn(*(list(a) + [None] * pad), **params),
                *in_specs)
        except Exception as e:
            if partial:
                continue
            raise MXNetError("infer_shape failed at %s(%s): %s"
                             % (node.op, node.name, e))
        if isinstance(out, (tuple, list)):
            for i, o in enumerate(out):
                specs[(id(node), i)] = o
        else:
            specs[(id(node), 0)] = out

    arg_names = sym.list_arguments()
    arg_shapes = [var_shape.get(n) for n in arg_names]
    if not partial and any(s is None for s in arg_shapes):
        missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
        raise MXNetError("infer_shape: undetermined arguments %r" % missing)
    out_shapes = []
    for n, oi in sym._outputs:
        s = specs.get((id(n), oi))
        out_shapes.append(tuple(s.shape) if s is not None else None)
    aux_shapes = [var_shape.get(n) for n in sym.list_auxiliary_states()]
    return arg_shapes, out_shapes, aux_shapes


def _eval_symbol(sym, feed, aux_updates=None):
    """Execute a symbol graph eagerly against a name->NDArray feed.

    If ``aux_updates`` is a dict, updated aux-state values (e.g.
    BatchNorm's new running stats, `_AUX_ARGS`) are collected into it
    keyed by aux variable name -- executors write them back after a
    training forward.
    """
    from ..ndarray import NDArray
    values = {}
    for node in sym._topo():
        if node.op is None:
            if node.name not in feed:
                raise MXNetError("missing input %r" % node.name)
            v = feed[node.name]
            values[(id(node), 0)] = getattr(v, "_data", v)
        else:
            out = _eval_node_value(node, values)
            if isinstance(out, (tuple, list)):
                for i, o in enumerate(out):
                    values[(id(node), i)] = o
            else:
                values[(id(node), 0)] = out
            if aux_updates is not None and node.op in _AUX_ARGS:
                op = get_op(node.op)
                for arg_name, out_idx in _AUX_ARGS[node.op].items():
                    pos = op.arg_names.index(arg_name)
                    if pos < len(node.inputs):
                        src, _ = node.inputs[pos]
                        if src.op is None and \
                                (id(node), out_idx) in values:
                            aux_updates[src.name] = \
                                values[(id(node), out_idx)]
    return [NDArray(values[(id(n), oi)]) for n, oi in sym._outputs]


def load_json(json_str):
    """Parse a ``-symbol.json`` graph (reference: ``sym.load_json``)."""
    data = json.loads(json_str)
    jnodes = data["nodes"]
    nodes = []
    for jn in jnodes:
        attrs = jn.get("attrs", jn.get("param", {})) or {}
        if jn["op"] == "null":
            node = _Node(None, jn["name"], attrs, [])
        else:
            opname = jn["op"]
            if opname not in OP_REGISTRY:
                raise MXNetError("symbol json references unknown op %r"
                                 % opname)
            node = _Node(opname, jn["name"], attrs, [])
        nodes.append(node)
    for jn, node in zip(jnodes, nodes):
        node.inputs = [(nodes[i], oi) for i, oi, *_ in jn["inputs"]]
        if node.op is not None:
            node.num_outputs = _probe_num_outputs(get_op(node.op), node)
    heads = data.get("heads", [[len(nodes) - 1, 0, 0]])
    return Symbol([(nodes[i], oi) for i, oi, *_ in heads])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())
