"""``mx.sym`` (reference: ``python/mxnet/symbol/``)."""
import sys as _sys

from .symbol import (Group, Symbol, Variable, load, load_json, var,
                     _eval_symbol)
from . import register as _register

_register.populate(_sys.modules[__name__].__dict__)
