"""Import-time codegen of the ``mx.sym.*`` surface (reference:
``python/mxnet/symbol/register.py``): same registry as ``mx.nd``, but the
generated functions build graph nodes instead of executing."""
from __future__ import annotations

import keyword

from ..ops.registry import OP_REGISTRY
from .symbol import Symbol, _make_node

_UNSET = object()


def _make_function(op, pyname):
    params = list(op.params)
    glb = {"_make_node": _make_node, "_op": op, "_UNSET": _UNSET,
           "_Symbol": Symbol}
    arg_bits = []
    if op.variadic:
        arg_bits.append("*data")
        call_args = "list(data)"
    else:
        for a in op.arg_names:
            arg_bits.append("%s=None" % a)
        call_args = ("[a for a in (%s,) if a is not None]"
                     % ", ".join(op.arg_names)) if op.arg_names else "[]"
    kw_bits = []
    for p in params:
        nm = p.name + ("_" if keyword.iskeyword(p.name) else "")
        kw_bits.append("%s=_UNSET" % nm)
    sig = ", ".join(arg_bits + kw_bits + ["name=None", "attr=None",
                                          "**kwargs"])
    kw_fill = "\n".join(
        "    if %s is not _UNSET: kwargs[%r] = %s"
        % (p.name + ("_" if keyword.iskeyword(p.name) else ""), p.name,
           p.name + ("_" if keyword.iskeyword(p.name) else ""))
        for p in params)
    src = (
        "def %s(%s):\n"
        "%s\n"
        "    return _make_node(%r, %s, kwargs, name=name)\n"
        % (pyname, sig, kw_fill or "    pass", op.name, call_args))
    exec(compile(src, "<mxnet_tpu-sym-gen>", "exec"), glb)
    fn = glb[pyname]
    fn.__doc__ = op.doc
    fn.__module__ = "mxnet_tpu.symbol"
    return fn


def populate(namespace):
    seen = {}
    for name, op in OP_REGISTRY.items():
        if not name.isidentifier():
            continue
        fn = seen.get((id(op), name))
        if fn is None:
            fn = _make_function(op, name)
            seen[(id(op), name)] = fn
        namespace.setdefault(name, fn)
    return namespace
