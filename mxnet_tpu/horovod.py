"""Horovod-style data-parallel API (reference: the ``mxnet+horovod``
integration -- ``hvd.init/rank/size/DistributedTrainer/broadcast_parameters``
pattern from the reference's large-batch examples).

TPU-native mapping: there is no MPI ring to manage -- processes join the
``jax.distributed`` world (one call), and the reduction primitives are
XLA collectives.  The API shape is kept so reference training scripts
port by changing the import.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .base import MXNetError
from .distributed import distributed_init
from .gluon.trainer import Trainer
from .ndarray import NDArray

_initialized = False


def init():
    """``hvd.init()``: join the multi-process world (env-driven; no-op
    when single-process)."""
    global _initialized
    distributed_init()
    _initialized = True


def rank():
    from .distributed import world
    return world()[1]


def size():
    from .distributed import world
    return world()[0]


def local_rank():
    return 0  # one process per host-slice in the jax runtime model


def allreduce(tensor, average=True, name=None):
    """Sum (or mean) a host-local array across workers."""
    from .distributed import host_allreduce, world
    x = tensor._data if isinstance(tensor, NDArray) else jnp.asarray(tensor)
    if world()[0] > 1:
        x = host_allreduce(x, average=average)
    return NDArray(x)


def grouped_allreduce(tensors, average=True, name=None):
    """``hvd.grouped_allreduce``: reduce a LIST of tensors in ONE
    flattened collective per dtype (``host_allreduce_bucketed``)
    instead of one RPC each -- the bucketed form metric/overflow
    reductions should use."""
    from .distributed import host_allreduce_bucketed, world
    vals = [t._data if isinstance(t, NDArray) else jnp.asarray(t)
            for t in tensors]
    if world()[0] > 1:
        vals = host_allreduce_bucketed(vals, average=average)
    return [NDArray(v) for v in vals]


def broadcast_parameters(params, root_rank=0):
    """Make every worker start from root's weights (reference:
    ``hvd.broadcast_parameters``) -- ONE bucketed collective for the
    whole parameter set, not one RPC per tensor."""
    from .distributed import host_broadcast_bucketed, world
    if world()[0] == 1:
        return
    items = list(params.items() if hasattr(params, "items") else params)
    # pass the device arrays through: the bucketed broadcast places
    # results back on each input's device/sharding (an np.asarray here
    # would land results on the DEFAULT device -- a remote TPU on
    # tunneled hosts)
    arrs = [(p.data() if hasattr(p, "data") else p) for _name, p in items]
    out = host_broadcast_bucketed([a._data for a in arrs], root=root_rank)
    for a, v in zip(arrs, out):
        a._data = v


class DistributedTrainer(Trainer):
    """``hvd.DistributedTrainer``: a Gluon Trainer whose gradients
    average across the process world before each update."""

    def __init__(self, params, optimizer, optimizer_params=None, **kwargs):
        super().__init__(params, optimizer, optimizer_params,
                         kvstore=None, **kwargs)
        from .distributed import world
        if not _initialized and world()[0] > 1:
            raise MXNetError("call horovod.init() first")

    def step(self, batch_size, ignore_stale_grad=False):
        from .distributed import world
        if world()[0] > 1:
            grads = [p.grad() for p in self._params
                     if p.grad_req != "null" and p._data is not None
                     and p._data._grad is not None]  # stale-grad guard
            reduced = grouped_allreduce(grads, average=True)
            for g, r in zip(grads, reduced):
                g._data = r._data
        super().step(batch_size, ignore_stale_grad)
