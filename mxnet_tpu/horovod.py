"""Horovod-style data-parallel API (reference: the ``mxnet+horovod``
integration -- ``hvd.init/rank/size/DistributedTrainer/broadcast_parameters``
pattern from the reference's large-batch examples).

TPU-native mapping: there is no MPI ring to manage -- processes join the
``jax.distributed`` world (one call), and the reduction primitives are
XLA collectives.  The API shape is kept so reference training scripts
port by changing the import.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .base import MXNetError
from .distributed import distributed_init
from .gluon.trainer import Trainer
from .ndarray import NDArray

_initialized = False


def init():
    """``hvd.init()``: join the multi-process world (env-driven; no-op
    when single-process)."""
    global _initialized
    distributed_init()
    _initialized = True


def rank():
    from .distributed import world
    return world()[1]


def size():
    from .distributed import world
    return world()[0]


def local_rank():
    return 0  # one process per host-slice in the jax runtime model


def allreduce(tensor, average=True, name=None):
    """Sum (or mean) a host-local array across workers."""
    from .distributed import host_allreduce, world
    x = tensor._data if isinstance(tensor, NDArray) else jnp.asarray(tensor)
    if world()[0] > 1:
        x = host_allreduce(x, average=average)
    return NDArray(x)


def broadcast_parameters(params, root_rank=0):
    """Make every worker start from root's weights (reference:
    ``hvd.broadcast_parameters``)."""
    from .distributed import host_broadcast, world
    if world()[0] == 1:
        return
    items = params.items() if hasattr(params, "items") else params
    for _name, p in items:
        arr = p.data() if hasattr(p, "data") else p
        # pass the device array through: host_broadcast places its
        # result back on the input's device (an np.asarray here would
        # both force a host fetch per parameter and land the result on
        # the DEFAULT device -- a remote TPU on tunneled hosts)
        arr._data = host_broadcast(arr._data, root_rank)


class DistributedTrainer(Trainer):
    """``hvd.DistributedTrainer``: a Gluon Trainer whose gradients
    average across the process world before each update."""

    def __init__(self, params, optimizer, optimizer_params=None, **kwargs):
        super().__init__(params, optimizer, optimizer_params,
                         kvstore=None, **kwargs)
        from .distributed import world
        if not _initialized and world()[0] > 1:
            raise MXNetError("call horovod.init() first")

    def step(self, batch_size, ignore_stale_grad=False):
        from .distributed import world
        if world()[0] > 1:
            for p in self._params:
                if p.grad_req == "null" or p._data is None \
                        or p._data._grad is None:
                    # mirror the base Trainer's stale-grad guard
                    continue
                g = p.grad()
                g._data = allreduce(g, average=True)._data
        super().step(batch_size, ignore_stale_grad)
