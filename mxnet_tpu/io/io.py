"""Legacy data iterators (reference: ``python/mxnet/io/io.py`` and the C++
iterators of ``src/io/``).

The reference's C++ ``ImageRecordIter`` (``iter_image_recordio_2.cc``) is a
threaded decode+augment pipeline over RecordIO shards; here
``ImageRecordIter`` wraps the PIL decode path with a thread pool and
double-buffered prefetch (``PrefetchingIter``), preserving the
``num_parts``/``part_index`` distributed sharding contract.
"""
from __future__ import annotations

import queue
import threading
import weakref
from collections import namedtuple

import numpy as np

from .. import sync as _sync
from ..base import MXNetError
from ..ndarray import NDArray, array

DataDesc = namedtuple("DataDesc", ["name", "shape"])


class DataBatch:
    """One batch (reference: ``DataBatch``)."""

    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Base iterator (reference: ``DataIter``)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def __next__(self):
        return self.next()

    def next(self):
        raise StopIteration

    @property
    def provide_data(self):
        return None

    @property
    def provide_label(self):
        return None


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: ``NDArrayIter``)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = self._init_data(data, data_name)
        self.label = self._init_data(label, label_name) if label is not None \
            else []
        self.num_data = self.data[0][1].shape[0]
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.reset()

    @staticmethod
    def _init_data(data, default_name):
        if isinstance(data, (np.ndarray, NDArray)):
            data = [(default_name, data)]
        elif isinstance(data, dict):
            data = list(data.items())
        elif isinstance(data, (list, tuple)):
            data = [("%s_%d" % (default_name, i) if i else default_name, d)
                    for i, d in enumerate(data)]
        out = []
        for name, d in data:
            if isinstance(d, NDArray):
                d = d.asnumpy()
            out.append((name, np.asarray(d)))
        return out

    @property
    def provide_data(self):
        return [DataDesc(n, (self.batch_size,) + d.shape[1:])
                for n, d in self.data]

    @property
    def provide_label(self):
        return [DataDesc(n, (self.batch_size,) + d.shape[1:])
                for n, d in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        self.order = np.random.permutation(self.num_data) if self.shuffle \
            else np.arange(self.num_data)

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        idx = self.order[self.cursor:self.cursor + self.batch_size]
        pad = 0
        if len(idx) < self.batch_size:
            if self.last_batch_handle == "discard":
                raise StopIteration
            pad = self.batch_size - len(idx)
            idx = np.concatenate([idx, self.order[:pad]])
        data = [array(d[idx]) for _, d in self.data]
        label = [array(d[idx]) for _, d in self.label]
        return DataBatch(data=data, label=label, pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches per epoch
    (reference: ``ResizeIter``)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur == self.size:
            raise StopIteration
        self.cur += 1
        try:
            return self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            return self.data_iter.next()

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label


class PrefetchingIter(DataIter):
    """Background-thread prefetch (reference: ``PrefetchingIter`` /
    dmlc ThreadedIter double-buffering).

    The producer closes over the *inner* iterator only and every put is
    stop-responsive, so a consumer that abandons iteration mid-epoch
    (GC without ``close()``) can never strand the thread parked on a
    full buffer -- the ``weakref.finalize`` stops it (the same
    discipline as ``mxnet_tpu.dataio.DeviceFeed``)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter supports one inner iter here")
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self._depth = prefetch_depth
        self._queue = None
        self._thread = None
        self._finalizer = None
        self._start()

    def _start(self):
        self._queue = q = queue.Queue(self._depth)
        self._stop = stop = _sync.Event(name="io.prefetch.stop")
        inner = self.iter

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def run():
            while not stop.is_set():
                try:
                    batch = inner.next()
                except StopIteration:
                    put(None)
                    return
                except Exception as e:       # re-raised at next()
                    put(e)
                    return
                if not put(batch):
                    return

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="mxnet_tpu.PrefetchingIter")
        from ..dataio.feed import _release_producer
        self._finalizer = weakref.finalize(self, _release_producer,
                                           q, stop)
        self._thread.start()

    def close(self):
        """Stop and join the producer; idempotent, safe mid-epoch."""
        if self._finalizer is not None:
            self._finalizer.detach()
        if self._stop is not None:
            self._stop.set()
        if self._queue is not None:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout=5)

    def reset(self):
        self.close()
        self.iter.reset()
        self._start()

    def next(self):
        item = self._queue.get()
        if item is None:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label


def MNISTIter(image=None, label=None, batch_size=128, shuffle=True,
              flat=False, **kwargs):
    """Reference: C++ ``iter_mnist.cc``; reads idx-ubyte files."""
    import gzip
    import struct as _struct

    def _read(img_path, lbl_path):
        op = gzip.open if img_path.endswith(".gz") else open
        with op(lbl_path, "rb") as f:
            _struct.unpack(">II", f.read(8))
            lbl = np.frombuffer(f.read(), np.uint8).astype(np.float32)
        with op(img_path, "rb") as f:
            _, n, h, w = _struct.unpack(">IIII", f.read(16))
            img = np.frombuffer(f.read(), np.uint8).reshape(n, 1, h, w)
        return img.astype(np.float32) / 255.0, lbl

    data, lbl = _read(image, label)
    if flat:
        data = data.reshape(len(data), -1)
    return NDArrayIter(data, lbl, batch_size, shuffle=shuffle)


def CSVIter(data_csv=None, data_shape=None, label_csv=None, label_shape=None,
            batch_size=128, **kwargs):
    """Reference: C++ ``iter_csv.cc``."""
    data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
    data = data.reshape((-1,) + tuple(data_shape))
    label = None
    if label_csv:
        label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
    return NDArrayIter(data, label, batch_size)


def ImageRecordIter(path_imgrec=None, data_shape=None, batch_size=128,
                    shuffle=False, rand_crop=False, rand_mirror=False,
                    mean_r=0, mean_g=0, mean_b=0, std_r=1, std_g=1, std_b=1,
                    num_parts=1, part_index=0, preprocess_threads=4,
                    resize=0, ctx=None, mesh=None, sharding=None,
                    feed_depth=None, dtype="float32", **kwargs):
    """High-throughput record iterator (reference:
    ``iter_image_recordio_2.cc :: ImageRecordIOParser2``); threaded PIL
    decode + augment + prefetch.

    With ``ctx``/``mesh``/``sharding`` the pipeline returns a
    :class:`mxnet_tpu.dataio.DeviceFeed` instead of a host prefetcher:
    decode+crop+mirror stay host-side on uint8, the batch ships compact
    over the wire, and cast + mean/std normalization run as one jitted
    program on the device after landing (docs/data_pipeline.md)."""
    from ..image import CastAug, CreateAugmenter, ImageIter

    aug = CreateAugmenter(data_shape, resize=resize, rand_crop=rand_crop,
                          rand_mirror=rand_mirror)
    if ctx is not None or mesh is not None or sharding is not None:
        from ..dataio import DeviceFeed, DeviceTransform
        aug = [a for a in aug if not isinstance(a, CastAug)]
        inner = ImageIter(batch_size, data_shape, path_imgrec=path_imgrec,
                          aug_list=aug, shuffle=shuffle,
                          num_parts=num_parts, part_index=part_index,
                          preprocess_threads=preprocess_threads,
                          dtype="uint8")
        mean_seq = (mean_r, mean_g, mean_b)
        std_seq = (std_r or 1, std_g or 1, std_b or 1)
        transform = DeviceTransform(
            dtype=dtype,
            mean=mean_seq if any(mean_seq) else None,
            std=std_seq if any(s != 1 for s in std_seq) else None)
        return DeviceFeed(inner, ctx=ctx, mesh=mesh, sharding=sharding,
                          transform=transform, depth=feed_depth)
    inner = ImageIter(batch_size, data_shape, path_imgrec=path_imgrec,
                      aug_list=aug, shuffle=shuffle, num_parts=num_parts,
                      part_index=part_index,
                      preprocess_threads=preprocess_threads)

    mean = np.array([mean_r, mean_g, mean_b], np.float32).reshape(3, 1, 1)
    std = np.array([std_r or 1, std_g or 1, std_b or 1],
                   np.float32).reshape(3, 1, 1)

    class _NormIter(DataIter):
        def __init__(self):
            super().__init__(batch_size)

        def reset(self):
            inner.reset()

        def next(self):
            d, labels, pad = inner.next_np()
            if d.shape[1] == 3 and (mean.any() or (std != 1).any()):
                d = (d - mean) / std
            return DataBatch(data=[array(d)], label=[array(labels)],
                             pad=pad)

        @property
        def provide_data(self):
            return [DataDesc("data", (batch_size,) + tuple(data_shape))]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", (batch_size,))]

    return PrefetchingIter(_NormIter())
