"""``mx.io`` (reference: ``python/mxnet/io/io.py``)."""
from .io import (DataBatch, DataDesc, DataIter, MNISTIter, NDArrayIter,
                 PrefetchingIter, ResizeIter, ImageRecordIter, CSVIter)
