"""``mx.nd.random`` namespace (reference: ``python/mxnet/ndarray/random.py``)."""
from __future__ import annotations

from ..ops.registry import get_op
from .ndarray import NDArray, invoke


def _sample(opname, shape, ctx, dtype, **params):
    from ..context import current_context
    if shape is None:
        shape = ()
    if isinstance(shape, int):
        shape = (shape,)
    out = invoke(get_op(opname), [], {"shape": tuple(shape), "dtype": dtype, **params})
    # follow the reference's placement contract: samples live on ctx
    # (default: the current context), not wherever the RNG computed
    return out.as_in_context(ctx if ctx is not None else current_context())


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kwargs):
    res = _sample("_random_uniform", shape, ctx, dtype, low=low, high=high)
    if out is not None:
        out._data = res._data
        return out
    return res


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kwargs):
    res = _sample("_random_normal", shape, ctx, dtype, loc=loc, scale=scale)
    if out is not None:
        out._data = res._data
        return out
    return res


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape or (1,), dtype, ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, **kwargs):
    return _sample("_random_gamma", shape, ctx, dtype, alpha=alpha, beta=beta)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, **kwargs):
    return _sample("_random_exponential", shape, ctx, dtype, lam=1.0 / scale)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, **kwargs):
    return _sample("_random_poisson", shape, ctx, dtype, lam=lam)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, **kwargs):
    return _sample("_random_negative_binomial", shape, ctx, dtype, k=k, p=p)


def randint(low, high, shape=None, dtype="int32", ctx=None, **kwargs):
    return _sample("_random_randint", shape, ctx, dtype, low=low, high=high)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kwargs):
    params = {"get_prob": get_prob, "dtype": dtype}
    if shape is not None:
        params["shape"] = shape
    return invoke(get_op("_sample_multinomial"), [data], params)


def shuffle(data, **kwargs):
    return invoke(get_op("_shuffle"), [data], {})


def uniform_like(data, low=0.0, high=1.0):
    return invoke(get_op("_random_uniform_like"), [data], {"low": low, "high": high})


def normal_like(data, loc=0.0, scale=1.0):
    return invoke(get_op("_random_normal_like"), [data], {"loc": loc, "scale": scale})
