"""Contrib namespace: control-flow operators + contrib op re-exports
(reference: ``python/mxnet/ndarray/contrib.py`` over
``src/operator/control_flow.cc``).

Control flow is where TPU-first design diverges hardest from the
reference: instead of an engine interpreting per-iteration subgraphs,
``foreach``/``while_loop``/``cond`` trace the Python body ONCE and lower
to ``lax.scan`` / ``lax.while_loop`` / ``lax.cond`` -- single compiled
programs with no per-step dispatch.  Gradients flow through the explicit
``data``/``loop_vars`` operands (the tape records one node for the whole
construct); arrays merely captured by the body closure are constants to
the gradient, so thread weights through the state if they must train.
"""
from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
from jax import lax

from .. import autograd
from ..base import MXNetError
from .ndarray import NDArray


def _aslist(x):
    if x is None:
        return [], True
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def _unlist(lst, single):
    if single:
        return lst[0] if lst else None
    return lst


class _FlowOp:
    """Just enough op-shape for the tape node naming."""
    def __init__(self, name):
        self.name = name
        self.num_diff_outputs = None


def _dispatch(name, pure_fn, inputs):
    """Run a pure multi-in/multi-out function with tape integration,
    mirroring ``invoke``'s recording semantics for a fused construct."""
    from . import bulk
    from .ndarray import _wrap_outputs
    vals = tuple(a._data for a in inputs)
    recording = autograd.is_recording() and \
        any(a._is_tracked() for a in inputs)
    if recording:
        raw, pull = jax.vjp(pure_fn, *vals)

        def vjp_fn(cts):
            # cotangents may arrive as pending bulk.LazyData (bulked
            # backward of downstream eager ops); a raw jax.vjp pull is
            # not LazyData-aware, so materialize before pulling
            return pull(bulk.materialize_tree(cts))
        return _wrap_outputs(_FlowOp(name), list(raw), list(inputs),
                             vjp_fn, {})
    return _wrap_outputs(_FlowOp(name), list(pure_fn(*vals)), None, None,
                         {})


def foreach(body, data, init_states):
    """Scan ``body`` over the leading axis of ``data`` (reference:
    ``contrib.foreach``): ``body(data_t, states) -> (out_t, states)``;
    returns (stacked outputs, final states).  Lowers to ONE compiled
    ``lax.scan`` -- the whole loop is a single XLA while op on TPU."""
    datas, single_data = _aslist(data)
    states, single_state = _aslist(init_states)
    n_data = len(datas)
    out_struct = {}

    def pure(*vals):
        dvals = vals[:n_data]
        svals = vals[n_data:]

        def step(carry, xs):
            with autograd.pause():
                st = [NDArray(c) for c in carry]
                xnd = [NDArray(x) for x in xs]
                out, new_st = body(_unlist(xnd, single_data),
                                   _unlist(st, single_state))
            outs, out_single = _aslist(out)
            news, new_single = _aslist(new_st)
            out_struct["out_single"] = out_single
            return tuple(n._data for n in news), \
                tuple(o._data for o in outs)

        carry, ys = lax.scan(step, tuple(svals), tuple(dvals))
        return tuple(ys) + tuple(carry)

    n_out = None
    outs = _dispatch("foreach", pure, datas + states)
    outs = outs if isinstance(outs, list) else [outs]
    n_out = len(outs) - len(states)
    stacked = outs[:n_out]
    finals = outs[n_out:]
    return _unlist(stacked, out_struct.get("out_single", True)), \
        _unlist(finals, single_state)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Reference: ``contrib.while_loop``.  Static-shape semantics: runs
    at most ``max_iterations`` steps of a ``lax.scan`` with an active
    mask (XLA needs a bound); per-step outputs beyond the dynamic stop
    are zero, matching the reference's padded-output contract."""
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations "
                         "(static bound for the compiled loop)")
    vars_, single = _aslist(loop_vars)
    meta = {}

    def pure(*vals):
        def step(carry, _):
            active, vs = carry
            with autograd.pause():
                vnd = [NDArray(v) for v in vs]
                c = cond(*vnd)
                out, new_vs = func(*vnd)
            outs, out_single = _aslist(out)
            news, _ = _aslist(new_vs)
            meta["out_single"] = out_single
            c_now = jnp.logical_and(active, c._data.astype(bool)
                                    .reshape(()))
            new_vals = tuple(
                jnp.where(c_now, n._data, v)
                for n, v in zip(news, vs))
            step_outs = tuple(
                jnp.where(c_now, o._data, jnp.zeros_like(o._data))
                for o in outs)
            return (c_now, new_vals), step_outs

        (active, final), ys = lax.scan(
            step, (jnp.asarray(True), tuple(vals)), None,
            length=int(max_iterations))
        return tuple(ys) + tuple(final)

    outs = _dispatch("while_loop", pure, vars_)
    outs = outs if isinstance(outs, list) else [outs]
    n_out = len(outs) - len(vars_)
    stacked = outs[:n_out]
    finals = outs[n_out:]
    return _unlist(stacked, meta.get("out_single", True)), \
        _unlist(finals, single)


def cond(pred, then_func, else_func, inputs=None):
    """Reference: ``contrib.cond``.  Both branches are traced once and
    compiled into a single ``lax.cond`` -- device-resident branching, no
    host sync on the predicate."""
    inputs, _ = _aslist(inputs)
    meta = {}

    def pure(pval, *vals):
        def mk(branch):
            def run(vs):
                with autograd.pause():
                    nds = [NDArray(v) for v in vs]
                    out = branch(*nds)
                outs, single = _aslist(out)
                meta["single"] = single
                return tuple(o._data for o in outs)
            return run
        return lax.cond(pval.astype(bool).reshape(()),
                        mk(then_func), mk(else_func), tuple(vals))

    pred_nd = pred if isinstance(pred, NDArray) else NDArray(
        jnp.asarray(pred))
    outs = _dispatch("cond", pure, [pred_nd] + inputs)
    outs = outs if isinstance(outs, list) else [outs]
    return _unlist(outs, meta.get("single", True))


def _export_contrib_ops():
    """Expose registered contrib-family ops as ``mx.nd.contrib.*``
    (reference surfaces them both flat and nested)."""
    from ..ops.registry import OP_REGISTRY
    from . import register as _register
    mod = sys.modules[__name__]
    wanted = ("box_iou", "box_nms", "ROIAlign", "ROIPooling",
              "quantize", "quantize_v2", "dequantize", "requantize",
              "quantized_fully_connected", "CTCLoss", "ctc_loss",
              "im2col", "col2im", "interleaved_matmul_selfatt_qk",
              "interleaved_matmul_selfatt_valatt",
              "interleaved_matmul_encdec_qk",
              "interleaved_matmul_encdec_valatt", "flash_attention")
    ns = {}
    _register.populate(ns)
    for name in wanted:
        if name in ns:
            setattr(mod, name, ns[name])


_export_contrib_ops()
