"""``mx.nd``: the imperative NDArray API (reference: ``python/mxnet/ndarray/``)."""
import sys as _sys

from .ndarray import (NDArray, array, arange, concat, concatenate, empty,
                      from_jax, full, invoke, load, moveaxis, ones,
                      onehot_encode, save, waitall, zeros)
from . import register as _register
from . import random  # noqa: F401
from . import sparse  # noqa: F401
from . import contrib  # noqa: F401

_register.populate(_sys.modules[__name__].__dict__)

# `mx.nd.op` namespace mirror (reference exposes ops both flat and nested)
op = _sys.modules[__name__]
