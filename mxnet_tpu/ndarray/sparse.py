"""Sparse NDArrays: CSR and row-sparse storage (reference:
``python/mxnet/ndarray/sparse.py :: CSRNDArray, RowSparseNDArray`` over
``src/ndarray/ndarray.cc`` kCSRStorage/kRowSparseStorage).

TPU-first design note.  XLA wants static shapes and dense tiles; truly
dynamic sparsity patterns defeat the MXU.  So sparse here is primarily a
**storage and communication** format -- embedding-gradient rows riding
the kvstore (``row_sparse_pull`` moves K rows, not the full table),
lazy/sparse optimizer updates touching only live rows, CSR datasets fed
batch-dense to the chip -- while *compute* lowers to dense-tiled
gather/scatter/segment ops with static output shapes (`jnp.take`,
``.at[].add``, ``jax.ops.segment_sum``).  That matches how the reference
uses these types in its headline workloads (sparse embeddings, libsvm
input), without fighting the hardware.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray

__all__ = ["CSRNDArray", "RowSparseNDArray", "csr_matrix",
           "row_sparse_array", "zeros", "array", "dot", "retain",
           "add", "elemwise_add"]


def _dev(ctx):
    return (ctx if ctx is not None else current_context()).jax_device()


class BaseSparseNDArray:
    """Common surface of the sparse storage types (reference:
    ``BaseSparseNDArray``)."""

    stype = None

    def __init__(self, shape, dtype, ctx):
        self.shape = tuple(shape)
        dtype = np.dtype(dtype)
        if dtype == np.float64:
            # JAX x64 is off: declaring float64 would silently disagree
            # with float32 storage, so normalize at the type boundary
            dtype = np.dtype(np.float32)
        self.dtype = dtype
        self._ctx = ctx

    @property
    def context(self):
        return self._ctx

    @property
    def ndim(self):
        return len(self.shape)

    def asnumpy(self):
        return np.asarray(self.todense()._data)

    def astype(self, dtype):
        raise NotImplementedError

    def todense(self) -> NDArray:
        """Densify (reference: ``tostype('default')``)."""
        raise NotImplementedError

    def tostype(self, stype):
        if stype == "default":
            return self.todense()
        if stype == self.stype:
            return self
        raise MXNetError("cannot convert %s to %s directly"
                         % (self.stype, stype))

    def copyto(self, other):
        raise MXNetError("copyto on sparse arrays: densify first "
                         "(tostype('default'))")

    def __repr__(self):
        return "<%s %s @%s>" % (type(self).__name__,
                                "x".join(map(str, self.shape)), self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference: ``CSRNDArray``).

    Components: ``indptr`` (n_rows+1,), ``indices`` (nnz,), ``data``
    (nnz,).  nnz is static per array instance -- XLA compiles one
    program per nnz class, the sparse analog of shape bucketing.
    """

    stype = "csr"

    def __init__(self, data, indices, indptr, shape, dtype=None, ctx=None):
        dtype = dtype or getattr(data, "dtype", np.float32)
        super().__init__(shape, dtype, ctx or current_context())
        dev = _dev(self._ctx)
        self._csr_data = jax.device_put(
            jnp.asarray(data, self.dtype), dev)
        self._csr_indices = jax.device_put(
            jnp.asarray(indices, jnp.int32), dev)
        self._csr_indptr = jax.device_put(
            jnp.asarray(indptr, jnp.int32), dev)
        if len(self.shape) != 2:
            raise MXNetError("CSR arrays are 2-D")

    # reference component accessors
    @property
    def data(self):
        return NDArray(self._csr_data)

    @property
    def indices(self):
        return NDArray(self._csr_indices)

    @property
    def indptr(self):
        return NDArray(self._csr_indptr)

    @property
    def nnz(self):
        return int(self._csr_data.shape[0])

    def todense(self):
        n, m = self.shape
        dense = jnp.zeros((n, m), self.dtype).at[
            self._row_ids(), self._csr_indices].add(self._csr_data)
        return NDArray(dense)

    def astype(self, dtype):
        return CSRNDArray(self._csr_data.astype(dtype), self._csr_indices,
                          self._csr_indptr, self.shape, dtype, self._ctx)

    def _row_ids(self):
        # row id per nonzero from indptr: static-shape searchsorted
        return jnp.searchsorted(self._csr_indptr,
                                jnp.arange(self.nnz, dtype=jnp.int32),
                                side="right") - 1

    def __getitem__(self, key):
        if isinstance(key, slice):
            start = key.start or 0
            stop = self.shape[0] if key.stop is None else key.stop
            if key.step not in (None, 1):
                raise MXNetError("CSR slicing supports step 1 only")
            d = self.todense()._data[start:stop]
            return csr_matrix(np.asarray(d), ctx=self._ctx,
                              dtype=self.dtype)
        raise MXNetError("CSR indexing supports row slices only")


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse tensor (reference: ``RowSparseNDArray``): a subset of
    rows is stored -- ``indices`` (k,) row ids, ``data`` (k, *row_shape).
    The embedding-gradient / kvstore workhorse."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape, dtype=None, ctx=None):
        dtype = dtype or getattr(data, "dtype", np.float32)
        super().__init__(shape, dtype, ctx or current_context())
        dev = _dev(self._ctx)
        self._rs_data = jax.device_put(jnp.asarray(data, self.dtype), dev)
        self._rs_indices = jax.device_put(
            jnp.asarray(indices, jnp.int32), dev)
        if self._rs_data.shape[1:] != self.shape[1:]:
            raise MXNetError(
                "row data shape %s does not match dense shape %s"
                % (self._rs_data.shape, self.shape))

    @property
    def data(self):
        return NDArray(self._rs_data)

    @property
    def indices(self):
        return NDArray(self._rs_indices)

    def todense(self):
        dense = jnp.zeros(self.shape, self.dtype).at[
            self._rs_indices].add(self._rs_data)
        return NDArray(dense)

    def astype(self, dtype):
        return RowSparseNDArray(self._rs_data.astype(dtype),
                                self._rs_indices, self.shape, dtype,
                                self._ctx)

    def retain(self, row_ids):
        """Keep only ``row_ids`` rows (reference: ``sparse.retain``).
        Static output shape: len(row_ids) rows; absent rows are zero."""
        rows = row_ids._data if isinstance(row_ids, NDArray) \
            else jnp.asarray(row_ids, jnp.int32)
        rows = rows.astype(jnp.int32)
        if self._rs_data.shape[0] == 0:
            picked = jnp.zeros((rows.shape[0],) + self.shape[1:],
                               self.dtype)
            return RowSparseNDArray(picked, rows, self.shape, self.dtype,
                                    self._ctx)
        # membership of each kept row in the stored set
        eq = rows[:, None] == self._rs_indices[None, :]   # (k', k)
        hit = eq.any(axis=1)
        src = jnp.argmax(eq, axis=1)
        picked = jnp.where(
            hit.reshape((-1,) + (1,) * (self._rs_data.ndim - 1)),
            self._rs_data[src], 0)
        return RowSparseNDArray(picked, rows, self.shape, self.dtype,
                                self._ctx)


# ----------------------------------------------------------------------
# Constructors (reference: sparse.py module functions)
# ----------------------------------------------------------------------

def _coerce_dense(arg1, dtype):
    """Dense-input dtype rule, matching ``mx.nd.array``: explicit dtype
    wins; float64 and non-float inputs become float32 (JAX x64 is off,
    so a declared float64 would silently disagree with storage)."""
    dense = np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray)
                       else arg1)
    if dtype is not None:
        return dense.astype(dtype)
    if dense.dtype in (np.float32, np.float16):
        return dense
    return dense.astype(np.float32)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray from (data, indices, indptr) or a dense
    array-like (reference: ``sparse.csr_matrix``)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("shape required with (data, indices, indptr)")
        return CSRNDArray(data, indices, indptr, shape, dtype, ctx)
    dense = _coerce_dense(arg1, dtype)
    if dense.ndim != 2:
        raise MXNetError("csr_matrix needs a 2-D input")
    mask = dense != 0
    indptr = np.concatenate([[0], np.cumsum(mask.sum(axis=1))]) \
        .astype(np.int32)
    indices = np.nonzero(mask)[1].astype(np.int32)
    data = dense[mask]
    return CSRNDArray(data, indices, indptr, dense.shape,
                      dtype or dense.dtype, ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or dense
    (reference: ``sparse.row_sparse_array``)."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if shape is None:
            # infer the dense shape (reference behavior): enough rows to
            # hold the largest index
            data = np.asarray(data)
            idx = np.asarray(indices)
            nrows = int(idx.max()) + 1 if idx.size else 0
            shape = (nrows,) + tuple(data.shape[1:])
        return RowSparseNDArray(data, indices, shape, dtype, ctx)
    dense = _coerce_dense(arg1, dtype)
    live = np.nonzero((dense != 0).reshape(dense.shape[0], -1)
                      .any(axis=1))[0].astype(np.int32)
    return RowSparseNDArray(dense[live], live, dense.shape,
                            dense.dtype, ctx)


def array(source, ctx=None, dtype=None):
    """Sparse-preserving array constructor (reference:
    ``sparse.array``)."""
    if isinstance(source, BaseSparseNDArray):
        return source
    return csr_matrix(source, ctx=ctx, dtype=dtype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    """Reference: ``sparse.zeros``."""
    if stype == "csr":
        return CSRNDArray(np.zeros((0,), dtype), np.zeros((0,), np.int32),
                          np.zeros((shape[0] + 1,), np.int32), shape,
                          dtype, ctx)
    if stype == "row_sparse":
        return RowSparseNDArray(
            np.zeros((0,) + tuple(shape[1:]), dtype),
            np.zeros((0,), np.int32), shape, dtype, ctx)
    raise MXNetError("unknown stype %r" % stype)


# ----------------------------------------------------------------------
# Operators (reference: dot_op / elemwise sparse kernels)
# ----------------------------------------------------------------------

def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """``csr · dense`` and ``csr^T · dense`` (reference: sparse ``dot``,
    the libsvm-data matmul).  Lowers to static-shape segment-sum --
    dense-tiled, no dynamic shapes."""
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        if transpose_b:
            raise MXNetError("transpose_b unsupported for csr dot")
        if rhs._data.ndim not in (1, 2):
            raise MXNetError("csr dot expects a 1-D or 2-D dense rhs")
        vec = rhs._data.ndim == 1
        rhs_mat = rhs._data[:, None] if vec else rhs._data
        rows = lhs._row_ids()
        cols = lhs._csr_indices
        vals = lhs._csr_data
        if not transpose_a:
            # out[r, :] = sum_nz vals * rhs[cols]
            contrib = vals[:, None] * rhs_mat[cols]        # (nnz, m)
            out = jax.ops.segment_sum(contrib, rows,
                                      num_segments=lhs.shape[0])
        else:
            contrib = vals[:, None] * rhs_mat[rows]
            out = jax.ops.segment_sum(contrib, cols,
                                      num_segments=lhs.shape[1])
        return NDArray(out[:, 0] if vec else out)
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        from . import dot as _dense_dot
        return _dense_dot(lhs, rhs, transpose_a=transpose_a,
                          transpose_b=transpose_b)
    raise MXNetError("sparse.dot supports csr x dense")


def retain(data, indices):
    """Reference: ``sparse.retain``."""
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    return data.retain(indices)


def elemwise_add(lhs, rhs):
    """row_sparse + row_sparse -> row_sparse (union of rows);
    sparse + dense and dense + dense -> dense."""
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray):
        if lhs.shape != rhs.shape:
            raise MXNetError("shape mismatch %s vs %s"
                             % (lhs.shape, rhs.shape))
        # sparse arrays hold CONCRETE index arrays (they are storage, not
        # traced compute -- module docstring), so the row union is exact
        # host-side: no padding, no phantom rows
        idx = np.concatenate([np.asarray(lhs._rs_indices),
                              np.asarray(rhs._rs_indices)])
        dat = jnp.concatenate([lhs._rs_data, rhs._rs_data])
        uniq, inv = np.unique(idx, return_inverse=True)
        summed = jax.ops.segment_sum(dat, jnp.asarray(inv.reshape(-1)),
                                     num_segments=len(uniq))
        return RowSparseNDArray(summed, uniq.astype(np.int32),
                                lhs.shape, lhs.dtype, lhs._ctx)
    if isinstance(lhs, NDArray) and isinstance(rhs, RowSparseNDArray):
        lhs, rhs = rhs, lhs
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, NDArray):
        out = rhs._data.at[lhs._rs_indices].add(lhs._rs_data)
        return NDArray(out)
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        return NDArray(lhs._data + rhs._data)
    raise MXNetError("unsupported operand storage types")


add = elemwise_add
