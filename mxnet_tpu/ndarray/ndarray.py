"""NDArray: the imperative tensor.

TPU-native re-design of the reference's ``src/ndarray/ndarray.cc ::
NDArray`` and ``python/mxnet/ndarray/ndarray.py``.  An NDArray wraps a
``jax.Array``.  JAX/PJRT's async dispatch replaces the reference's
dependency engine (SURVEY.md L1): op calls return immediately with a
future-backed array; ``asnumpy()`` / ``wait_to_read()`` are the sync
points, where device-side errors surface (the reference's
``MXNDArraySyncCopyToCPU`` contract).

Mutation semantics (`a += b`, ``a[...] = v``, optimizer updates) are
version-rebinding: the Python object stays, its ``_data`` handle moves to a
new functional array (donation lets XLA reuse the buffer).  Basic-slice
*views* therefore copy rather than alias -- the one intentional divergence
from the reference, documented here.
"""
from __future__ import annotations

import contextlib
import functools
import os
import struct
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import autograd
from .. import profiling as _profiling
from .. import random as _random_mod
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..context import Context, current_context
from ..ops.registry import Op, get_op
from . import bulk

__all__ = ["NDArray", "array", "empty", "zeros", "ones", "full", "arange",
           "concat", "concatenate", "save", "load", "invoke", "waitall",
           "moveaxis", "from_jax", "onehot_encode"]

_MX_DTYPE_TO_FLAG = {
    np.dtype("float32"): 0, np.dtype("float64"): 1, np.dtype("float16"): 2,
    np.dtype("uint8"): 3, np.dtype("int32"): 4, np.dtype("int8"): 5,
    np.dtype("int64"): 6,
}
_FLAG_TO_MX_DTYPE = {v: k for k, v in _MX_DTYPE_TO_FLAG.items()}
# bfloat16 is TPU-native; give it a flag outside the reference's range.
_MX_DTYPE_TO_FLAG[np.dtype(jnp.bfloat16.dtype)] = 100
_FLAG_TO_MX_DTYPE[100] = np.dtype(jnp.bfloat16.dtype)


def waitall():
    """Block until all async work completes (reference:
    ``mx.nd.waitall`` / ``Engine::WaitForAll``).

    Device-side errors raised by in-flight computations surface HERE, at
    the sync point -- the reference's contract (``threaded_engine.cc ::
    OnCompleteStatic`` re-throws captured exceptions at WaitForAll /
    WaitToRead).  Errors from deleted arrays whose computations already
    failed cannot be resurrected, but every live array's pending work is
    drained and the first failure propagates.
    """
    t0 = time.perf_counter() if _telemetry._ENABLED else None
    bulk.flush()
    if hasattr(jax, "effects_barrier"):
        jax.effects_barrier()
    for d in jax.live_arrays():
        if isinstance(d, jax.core.Tracer):
            continue
        d.block_until_ready()
    if t0 is not None:
        _telemetry.hooks.host_sync("waitall", time.perf_counter() - t0)


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _amp_active():
    import sys
    amp_mod = sys.modules.get("mxnet_tpu.amp")
    return amp_mod is not None and amp_mod.is_active()


class NDArray:
    """An n-dimensional array on a device context."""

    __slots__ = ("_buf", "_grad", "_grad_req", "_ag_node", "_ag_out_index",
                 "__weakref__")

    def __init__(self, data, ctx=None):
        if isinstance(data, NDArray):
            data = data._buf
        if isinstance(data, bulk.LazyData):
            if data._concrete is not None:
                data = data._concrete
            elif ctx is not None:
                data = jax.device_put(data.materialize(), ctx.jax_device())
        elif ctx is not None and not _is_traced(data):
            data = jax.device_put(jnp.asarray(data), ctx.jax_device())
        elif not isinstance(data, jax.Array) and not _is_traced(data):
            data = jnp.asarray(data)
        self._buf = data
        self._grad = None
        self._grad_req = "write"
        self._ag_node = None
        self._ag_out_index = 0

    # -- data handle ---------------------------------------------------
    # ``_data`` is the concrete jax.Array handle; reading it is a sync
    # point for the bulked eager queue (the reference's WaitToRead).
    # Shape/dtype queries go through ``_buf`` and never force execution.
    @property
    def _data(self):
        buf = self._buf
        if isinstance(buf, bulk.LazyData):
            buf = buf.materialize()
            self._buf = buf
        return buf

    @_data.setter
    def _data(self, value):
        if isinstance(value, bulk.LazyData) and value._concrete is not None:
            value = value._concrete
        self._buf = value

    # -- basic properties ---------------------------------------------
    @property
    def shape(self):
        return tuple(self._buf.shape)

    @property
    def dtype(self):
        return np.dtype(self._buf.dtype)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return len(self._buf.shape)

    @property
    def stype(self):
        return "default"

    @property
    def context(self):
        if _is_traced(self._data):
            return current_context()
        # for a multi-host global array (SPMD global mesh), the context
        # must name a device THIS process can address, by its LOCAL
        # ordinal -- a raw global device id indexes out of the
        # per-worker device list Context.jax_device resolves against
        sharding = getattr(self._data, "sharding", None)
        addr = getattr(sharding, "addressable_devices", None)
        dev = min(addr, key=lambda d: d.id) if addr else \
            next(iter(self._data.devices()))
        name = "cpu" if dev.platform == "cpu" else "tpu"
        from ..context import _jax_devices_for
        try:
            ordinal = _jax_devices_for(name).index(dev)
        except ValueError:
            ordinal = dev.id
        return Context(name, ordinal)

    ctx = context

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        return transpose(self)

    # -- sync / conversion --------------------------------------------
    def asnumpy(self):
        """Blocking copy to host (reference: ``MXNDArraySyncCopyToCPU``)."""
        if _telemetry._ENABLED:
            t0 = time.perf_counter()
            out = np.asarray(self._data)
            _telemetry.hooks.host_sync("asnumpy",
                                       time.perf_counter() - t0)
            return out
        return np.asarray(self._data)

    def __array__(self, dtype=None, copy=None):
        """NumPy conversion protocol: one bulk device fetch.  Without
        this, np.asarray falls back to elementwise ``__getitem__`` --
        N separate device gathers, each a full round-trip on a remote
        device."""
        if copy is False:
            raise ValueError(
                "converting an NDArray to numpy always copies from the "
                "device buffer; copy=False cannot be satisfied")
        a = self.asnumpy()
        if dtype is not None:
            a = a.astype(dtype, copy=False)
        return a

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("asscalar: array is not scalar-sized")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if not self.shape:
            raise MXNetError("len() of 0-d NDArray")
        return self.shape[0]

    def wait_to_read(self):
        if _telemetry._ENABLED:
            t0 = time.perf_counter()
            if not _is_traced(self._data):
                self._data.block_until_ready()
            _telemetry.hooks.host_sync("wait_to_read",
                                       time.perf_counter() - t0)
            return
        if not _is_traced(self._data):
            self._data.block_until_ready()

    def wait_to_write(self):
        self.wait_to_read()

    def tolist(self):
        return self.asnumpy().tolist()

    def astype(self, dtype, copy=True):
        return NDArray(self._data.astype(np.dtype(dtype)))

    def copy(self):
        return NDArray(jnp.array(self._data))

    def copyto(self, other):
        """Copy to another array or context (reference: ``CopyFromTo``)."""
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()))
        if isinstance(other, NDArray):
            other._data = jax.device_put(self._data, next(iter(other._data.devices()))) \
                if not _is_traced(other._data) else self._data
            return other
        raise MXNetError("copyto: bad target %r" % (other,))

    def as_in_context(self, ctx):
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device()))

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype):
        if stype != "default":
            raise MXNetError("sparse storage not supported in this build")
        return self

    # -- autograd ------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer (reference: ``ndarray.py ::
        attach_grad``); marks this array as a differentiable leaf,
        detaching it from any previously recorded graph."""
        self._grad = NDArray(jnp.zeros_like(self._data))
        self._grad_req = grad_req
        self._ag_node = None
        self._ag_out_index = 0

    def detach(self):
        out = NDArray(self._data)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def _is_tracked(self):
        return self._ag_node is not None or \
            (self._grad is not None and self._grad_req != "null")

    # -- indexing ------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, NDArray):
            kd = key._data
            if kd.dtype == jnp.bool_:
                return NDArray(self._data[np.asarray(kd)])
            return NDArray(jnp.take(self._data, kd.astype(jnp.int32), axis=0))
        key = tuple(k._data if isinstance(k, NDArray) else k for k in key) \
            if isinstance(key, tuple) else key
        return NDArray(self._data[key])

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        if isinstance(key, NDArray):
            key = key._data.astype(jnp.int32)
        if isinstance(key, tuple):
            key = tuple(k._data if isinstance(k, NDArray) else k for k in key)
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            v = jnp.broadcast_to(jnp.asarray(value, dtype=self._data.dtype),
                                 self.shape)
            # keep the array on its committed device (a bare jnp.asarray
            # would land on the default device)
            if not _is_traced(self._data) and not _is_traced(v):
                v = jax.device_put(v, next(iter(self._data.devices())))
            self._data = v
        else:
            self._data = self._data.at[key].set(value)

    # -- arithmetic (rebinding in-place forms) ------------------------
    def _binop(self, other, opname, reverse=False):
        if not isinstance(other, NDArray) and np.isscalar(other):
            # scalar operand -> dedicated *_scalar op (reference
            # semantics); keeps the scalar a compile-time param instead
            # of a per-call host->device transfer
            sop = _SCALAR_OP.get((opname, reverse))
            if sop is not None:
                return invoke(get_op(sop), [self],
                              {"scalar": float(other)})
        if isinstance(other, NDArray):
            rhs = other
        elif _is_traced(self._data) or len(self._data.devices()) != 1:
            rhs = NDArray(jnp.asarray(other, dtype=self._data.dtype))
        else:
            arr = np.asarray(other, dtype=self._data.dtype)
            rhs = NDArray(jax.device_put(
                arr, next(iter(self._data.devices()))))
        lhs = self
        if reverse:
            lhs, rhs = rhs, lhs
        return invoke(get_op(opname), [lhs, rhs], {})

    def __add__(self, o):
        return self._binop(o, "elemwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div")

    def __rtruediv__(self, o):
        return self._binop(o, "elemwise_div", reverse=True)

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod")

    def __pow__(self, o):
        return self._binop(o, "broadcast_power")

    def __rpow__(self, o):
        return self._binop(o, "broadcast_power", reverse=True)

    def __matmul__(self, o):
        return invoke(get_op("dot"), [self, o], {})

    def __neg__(self):
        return invoke(get_op("negative"), [self], {})

    def __abs__(self):
        return invoke(get_op("abs"), [self], {})

    def __eq__(self, o):
        return self._binop(o, "broadcast_equal")

    def __ne__(self, o):
        return self._binop(o, "broadcast_not_equal")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal")

    __hash__ = object.__hash__

    def _inplace_guard(self):
        # Mirrors the reference's restriction: in-place writes to an array
        # that participates in a recorded graph would corrupt the tape.
        if autograd.is_recording() and self._is_tracked():
            raise MXNetError(
                "in-place operation on an array that requires grad inside "
                "autograd.record() is not allowed; use out-of-place ops")

    def __iadd__(self, o):
        self._inplace_guard()
        self._data = self.__add__(o)._data
        self._ag_node = None
        return self

    def __isub__(self, o):
        self._inplace_guard()
        self._data = self.__sub__(o)._data
        self._ag_node = None
        return self

    def __imul__(self, o):
        self._inplace_guard()
        self._data = self.__mul__(o)._data
        self._ag_node = None
        return self

    def __itruediv__(self, o):
        self._inplace_guard()
        self._data = self.__truediv__(o)._data
        self._ag_node = None
        return self

    def __repr__(self):
        if _is_traced(self._data):
            return "<NDArray traced %s %s>" % (self.shape, self.dtype)
        return "%s\n<NDArray %s @%s>" % (
            np.array2string(self.asnumpy(), precision=4, suppress_small=True),
            "x".join(str(s) for s in self.shape) or "scalar", self.context)

    # -- common method forms of ops (subset of the generated surface) --
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return invoke(get_op("Reshape"), [self], {"shape": shape, **kwargs})

    def reshape_like(self, other):
        return invoke(get_op("reshape_like"), [self, other], {})

    def flatten(self):
        return invoke(get_op("Flatten"), [self], {})

    def transpose(self, axes=None):
        return invoke(get_op("transpose"), [self], {"axes": axes})

    def swapaxes(self, dim1, dim2):
        return invoke(get_op("swapaxes"), [self], {"dim1": dim1, "dim2": dim2})

    def expand_dims(self, axis):
        return invoke(get_op("expand_dims"), [self], {"axis": axis})

    def squeeze(self, axis=None):
        return invoke(get_op("squeeze"), [self], {"axis": axis})

    def broadcast_to(self, shape):
        return invoke(get_op("broadcast_to"), [self], {"shape": shape})

    def broadcast_like(self, other):
        return invoke(get_op("broadcast_like"), [self, other], {})

    def sum(self, axis=None, keepdims=False):
        return invoke(get_op("sum"), [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return invoke(get_op("mean"), [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return invoke(get_op("prod"), [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return invoke(get_op("max"), [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return invoke(get_op("min"), [self], {"axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None):
        return invoke(get_op("argmax"), [self], {"axis": axis})

    def argmin(self, axis=None):
        return invoke(get_op("argmin"), [self], {"axis": axis})

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke(get_op("norm"), [self], {"ord": ord, "axis": axis,
                                               "keepdims": keepdims})

    def clip(self, a_min, a_max):
        return invoke(get_op("clip"), [self], {"a_min": a_min, "a_max": a_max})

    def abs(self):
        return invoke(get_op("abs"), [self], {})

    def sqrt(self):
        return invoke(get_op("sqrt"), [self], {})

    def square(self):
        return invoke(get_op("square"), [self], {})

    def exp(self):
        return invoke(get_op("exp"), [self], {})

    def log(self):
        return invoke(get_op("log"), [self], {})

    def sigmoid(self):
        return invoke(get_op("sigmoid"), [self], {})

    def tanh(self):
        return invoke(get_op("tanh"), [self], {})

    def relu(self):
        return invoke(get_op("relu"), [self], {})

    def softmax(self, axis=-1):
        return invoke(get_op("softmax"), [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return invoke(get_op("log_softmax"), [self], {"axis": axis})

    def take(self, indices, axis=0, mode="clip"):
        return invoke(get_op("take"), [self, indices], {"axis": axis, "mode": mode})

    def pick(self, index, axis=-1, keepdims=False):
        return invoke(get_op("pick"), [self, index], {"axis": axis, "keepdims": keepdims})

    def one_hot(self, depth, **kw):
        return invoke(get_op("one_hot"), [self], {"depth": depth, **kw})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return invoke(get_op("topk"), [self], {"axis": axis, "k": k,
                                               "ret_typ": ret_typ,
                                               "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return invoke(get_op("sort"), [self], {"axis": axis, "is_ascend": is_ascend})

    def argsort(self, axis=-1, is_ascend=True):
        return invoke(get_op("argsort"), [self], {"axis": axis, "is_ascend": is_ascend})

    def flip(self, axis):
        return invoke(get_op("reverse"), [self], {"axis": axis})

    def tile(self, reps):
        return invoke(get_op("tile"), [self], {"reps": reps})

    def repeat(self, repeats, axis=None):
        return invoke(get_op("repeat"), [self], {"repeats": repeats, "axis": axis})

    def slice_axis(self, axis, begin, end):
        return invoke(get_op("slice_axis"), [self], {"axis": axis, "begin": begin,
                                                     "end": end})

    def zeros_like(self):
        return invoke(get_op("zeros_like"), [self], {})

    def ones_like(self):
        return invoke(get_op("ones_like"), [self], {})


# ----------------------------------------------------------------------
# Op dispatch
# ----------------------------------------------------------------------

def _wrap_outputs(op, raw, inputs_for_tape, vjp_fn, params):
    multi = isinstance(raw, (tuple, list))
    raws = list(raw) if multi else [raw]
    outs = [NDArray(r) for r in raws]
    if vjp_fn is not None:
        node = autograd.TapeNode(inputs_for_tape, vjp_fn, len(raws),
                                 name=op.name)
        node._out_avals = [(tuple(r.shape), r.dtype) for r in raws]
        ndiff = op.num_diff_outputs if op.num_diff_outputs is not None else len(raws)
        for i, o in enumerate(outs):
            if i < ndiff:
                o._ag_node = node
                o._ag_out_index = i
    return outs if multi else outs[0]


# scalar-operand op table for NDArray._binop (reference: the
# ``_plus_scalar``-family ops backing ndarray's operator overloads)
_SCALAR_OP = {
    ("elemwise_add", False): "_plus_scalar",
    ("elemwise_add", True): "_plus_scalar",
    ("elemwise_sub", False): "_minus_scalar",
    ("elemwise_sub", True): "_rminus_scalar",
    ("elemwise_mul", False): "_mul_scalar",
    ("elemwise_mul", True): "_mul_scalar",
    ("elemwise_div", False): "_div_scalar",
    ("elemwise_div", True): "_rdiv_scalar",
    ("broadcast_power", False): "_power_scalar",
    ("broadcast_power", True): "_rpower_scalar",
    ("broadcast_mod", False): "_mod_scalar",
    ("broadcast_equal", False): "_equal_scalar",
    ("broadcast_equal", True): "_equal_scalar",
    ("broadcast_not_equal", False): "_not_equal_scalar",
    ("broadcast_not_equal", True): "_not_equal_scalar",
    ("broadcast_greater", False): "_greater_scalar",
    ("broadcast_greater", True): "_lesser_scalar",
    ("broadcast_greater_equal", False): "_greater_equal_scalar",
    ("broadcast_greater_equal", True): "_lesser_equal_scalar",
    ("broadcast_lesser", False): "_lesser_scalar",
    ("broadcast_lesser", True): "_greater_scalar",
    ("broadcast_lesser_equal", False): "_lesser_equal_scalar",
    ("broadcast_lesser_equal", True): "_greater_equal_scalar",
}


# ----------------------------------------------------------------------
# Eager dispatch jit cache (SURVEY §7 hard-part #1): every imperative op
# call runs through a persistent compiled primitive keyed on
# (op, arg shapes/dtypes, params, amp policy), so non-hybridized training
# pays one XLA executable launch instead of tens of µs of Python+trace
# per op.  The reference's analog is the engine's cached fcompute path.
# ----------------------------------------------------------------------
_EAGER_JIT_CACHE = {}
_EAGER_JIT_ENABLED = os.environ.get("MXNET_TPU_EAGER_JIT", "1") != "0"


def _canon_param(v):
    if isinstance(v, (list, tuple)):
        return tuple(_canon_param(x) for x in v)
    if isinstance(v, np.ndarray):
        return ("__np__", v.shape, str(v.dtype), v.tobytes())
    return v


# Params that vary per call (per-step lr/wd schedules, step counters,
# arbitrary `x + c` scalars): traced as weak-typed jit arguments so a
# new VALUE does not mean a new XLA compilation.  Everything else
# (flags, shapes, clip thresholds with Python control flow) stays
# static in the key.  The retrace auditor (mxnet_tpu.analysis.retrace)
# cross-references this set against the registry's param specs.
_DYNAMIC_PARAMS = frozenset(("lr", "wd", "rescale_grad", "scalar", "t"))


def _eager_jit_fn(op, params, present, total_args):
    """Return ``(jfn, dyn_names, sig)`` -- a cached jitted callable, the
    names of params it takes as traced scalars, and the cache key -- or
    ``(None, (), None)`` when the call is unjittable (unhashable
    params)."""
    if not _EAGER_JIT_ENABLED:
        return None, (), None
    dyn_names = tuple(sorted(
        k for k in params
        if k in _DYNAMIC_PARAMS and isinstance(params[k], (int, float))
        and not isinstance(params[k], bool)))
    try:
        psig = tuple(sorted((k, _canon_param(v))
                            for k, v in params.items()
                            if k not in dyn_names))
        hash(psig)
    except TypeError:
        return None, (), None
    from .. import amp as _amp
    amp_token = _amp.policy_token() if _amp_active() else None
    sig = (op.name, present, total_args, psig, dyn_names, amp_token)
    entry = _EAGER_JIT_CACHE.get(sig)
    if entry is None:
        fcompute = op.fcompute
        stateful = op.stateful_rng
        opname = op.name
        static_kwargs = {k: v for k, v in params.items()
                         if k not in dyn_names}
        do_amp = amp_token is not None

        def f(dyn_vals, *pd):
            if stateful:
                rng_key, pd = pd[0], pd[1:]
            full = [None] * total_args
            for i, d in zip(present, pd):
                full[i] = d
            if do_amp:
                from .. import amp as _amp2
                # casts INSIDE the differentiated function: the cast vjp
                # returns fp32 gradients (master weights for free)
                full = _amp2.apply_op_casts(opname, full)
            kwargs = dict(static_kwargs)
            kwargs.update(zip(dyn_names, dyn_vals))
            if stateful:
                return fcompute(rng_key, *full, **kwargs)
            return fcompute(*full, **kwargs)

        entry = (jax.jit(f), f, stateful)
        # suppression invariant: sig space = op set x call arities x
        # static-param values actually used -- program-bounded, and a
        # recorded backward resolves its forward through this table
        # (_eager_bwd_fn), so LRU eviction here would KeyError an
        # in-flight autograd tape.  Retrace growth is observable via
        # the compile_event cache_size payload instead.
        _EAGER_JIT_CACHE[sig] = entry  # mxlint: disable=unbounded-shape-cache
        if _telemetry._ENABLED:
            _emit_eager_compile(sig)
    return entry[0], dyn_names, sig


def _emit_eager_compile(sig):
    """A fresh eager-dispatch cache entry was created: emit a compile
    event.  When the op already holds a same-arity entry, this is a
    RETRACE -- a static param (or the amp policy) changed value, the
    exact class of recompile-per-step regression the static auditor
    flagged for LAMB's ``t`` -- and the payload names the params that
    differ so the log says *why* XLA compiled again."""
    opname, present, total, psig, _dyn, amp_token = sig
    prior = [s for s in _EAGER_JIT_CACHE
             if s[0] == opname and s[1] == present and s[2] == total
             and s is not sig]
    changed = []
    if prior:
        prev = prior[-1]
        prev_ps, cur_ps = dict(prev[3]), dict(psig)
        changed = sorted(str(k) for k in set(prev_ps) | set(cur_ps)
                         if prev_ps.get(k) != cur_ps.get(k))
        if prev[5] != amp_token:
            changed.append("amp_policy")
    _telemetry.hooks.compile_event(
        "eager_jit", retrace=bool(prior), op=opname,
        cache_size=len(_EAGER_JIT_CACHE), changed=changed)


# Per-sig cached BACKWARD executables for recorded eager ops.  Without
# this every `autograd.record()`-scoped op call pays a fresh jax.vjp
# trace -- the dominant term of the imperative/hybridized gap (SURVEY
# §7 hard-part #1).  The cached backward is recompute-based (jax.vjp of
# the forward inside one jit, cotangents applied in the same program):
# the op's residuals are rebuilt from its inputs, trading a little FLOP
# for never tracing at dispatch time -- the per-op analog of
# ``jax.checkpoint``.
_EAGER_BWD_CACHE = {}


def _eager_bwd_fn(sig):
    bwd = _EAGER_BWD_CACHE.get(sig)
    if bwd is None:
        _jfn, f, stateful = _EAGER_JIT_CACHE[sig]

        def b(dyn_vals, key, pd, cts):
            if stateful:
                def fwd(*p):
                    return f(dyn_vals, key, *p)
            else:
                def fwd(*p):
                    return f(dyn_vals, *p)
            _, pull = jax.vjp(fwd, *pd)
            return pull(cts)

        bwd = jax.jit(b)
        # suppression invariant: strictly a subset of _EAGER_JIT_CACHE's
        # sig space (only recorded ops), bounded by the same program
        # invariant documented there.
        _EAGER_BWD_CACHE[sig] = bwd  # mxlint: disable=unbounded-shape-cache
    return bwd


def invoke(op: Op, tensor_args, kwargs, out=None):
    """Dispatch one op eagerly (reference: ``Imperative::Invoke`` in
    ``src/imperative/imperative.cc``; shape/type inference + engine push
    collapse into a single traced JAX call here)."""
    if _telemetry._ENABLED:
        _telemetry.hooks.op_dispatch(op.name)
    kwargs = dict(kwargs)
    kwargs.pop("name", None)
    params = op.param_defaults()
    for k, v in kwargs.items():
        if k not in params and not any(p.name == k for p in op.params):
            raise MXNetError("op %s: unknown argument %r" % (op.name, k))
        params[k] = v
    if any(p.name == "training" for p in op.params) and "training" not in kwargs:
        params["training"] = autograd.is_training()

    # single-device reference only: committing a converted operand to
    # one device of a SHARDED operand's set would break the jit call
    ref_device = None
    for a in tensor_args:
        if not isinstance(a, NDArray):
            continue
        b = a._buf
        if isinstance(b, bulk.LazyData):
            if b._concrete is not None:
                b = b._concrete
            elif b.device is not None:
                ref_device = b.device
                break
            else:
                continue
        if not _is_traced(b) and len(b.devices()) == 1:
            ref_device = next(iter(b.devices()))
            break
    nds = []
    datas = []
    for a in tensor_args:
        if a is None:
            nds.append(None)
            datas.append(None)
        elif isinstance(a, NDArray):
            nds.append(a)
            b = a._buf
            if isinstance(b, bulk.LazyData) and b._concrete is not None:
                b = b._concrete
                a._buf = b
            datas.append(b)
        else:
            # place converted operands WITH the tensor operands -- the
            # default device may be a remote TPU, and a stray transfer
            # per op call is a tunnel round-trip
            raw = np.asarray(a)
            nd = NDArray(jax.device_put(raw, ref_device)
                         if ref_device is not None else jnp.asarray(raw))
            nds.append(nd)
            datas.append(nd._data)

    key = _random_mod.next_key() if op.stateful_rng else None

    present = tuple(i for i, d in enumerate(datas) if d is not None)
    pdatas = [datas[i] for i in present]

    jfn, dyn_names, sig = _eager_jit_fn(op, params, present, len(datas))
    if jfn is not None:
        dyn_vals = tuple(float(params[n]) for n in dyn_names)
        call = functools.partial(jfn, dyn_vals, key) if op.stateful_rng \
            else functools.partial(jfn, dyn_vals)
    else:
        # unjittable params (rare): eager fallback -- needs concrete data
        datas = [bulk.materialize(d) for d in datas]
        fn = functools.partial(op.fcompute, key) if op.stateful_rng \
            else op.fcompute

        def call(*pd):
            full = list(datas)
            for i, d in zip(present, pd):
                full[i] = bulk.materialize(d)
            if _amp_active():
                from .. import amp as _amp
                full = _amp.apply_op_casts(op.name, full)
            return fn(*full, **params)

    # bulked dispatch: append to the pending region instead of launching
    # one XLA program per op (reference: engine op bulking)
    bulkable = (jfn is not None and bulk.enabled()
                and not any(_is_traced(d) for d in pdatas))

    def dispatch():
        if bulkable:
            args = ((dyn_vals, key) + tuple(pdatas)) if op.stateful_rng \
                else ((dyn_vals,) + tuple(pdatas))
            return bulk.enqueue(jfn, sig, args, device=ref_device)
        return call(*pdatas)

    from .. import profiler as _profiler
    scope = _profiler.scope("mx." + op.name) \
        if _profiler._scopes_enabled else contextlib.nullcontext()
    recording = autograd.is_recording() and any(
        n is not None and n._is_tracked() for n in nds)
    with scope:
        if recording:
            if jfn is not None:
                # cached-executable forward + cached recompute-based
                # backward: no tracing on either pass after warmup
                raw = dispatch()
                bwd = _eager_bwd_fn(sig)
                pd_tuple = tuple(pdatas)
                dv, kk = dyn_vals, key

                def vjp_fn(cts):
                    cts_flat, _ = jax.tree_util.tree_flatten(
                        cts, is_leaf=lambda x: isinstance(x, bulk.LazyData))
                    traced = any(_is_traced(x) for x in pd_tuple) or \
                        any(_is_traced(x) for x in cts_flat)
                    if bulk.enabled() and not traced:
                        # backward bulking: the cached bwd executable
                        # joins the pending region like any forward op.
                        # Traced operands (backward replayed under an
                        # outer jax trace) must NOT enter the module
                        # queue: they would leak out of the trace and
                        # x.devices() on a tracer raises -- mirror the
                        # forward's bulkable guard and call directly.
                        return bulk.enqueue(bwd, ("bwd", sig),
                                            (dv, kk, pd_tuple, cts))
                    pd = tuple(bulk.materialize(x) for x in pd_tuple)
                    return bwd(dv, kk, pd, bulk.materialize_tree(cts))
            else:
                raw, pull = jax.vjp(
                    call, *[bulk.materialize(d) for d in pdatas])

                def vjp_fn(cts, _pull=pull):
                    # same LazyData hazard as the jitted path: bulked
                    # cotangents must be concrete before the raw pull
                    return _pull(bulk.materialize_tree(cts))
            tape_inputs = [nds[i] for i in present]
            result = _wrap_outputs(op, raw, tape_inputs, vjp_fn, params)
        else:
            raw = dispatch()
            result = _wrap_outputs(op, raw, None, None, params)

    if _profiling._ENABLED and jfn is not None and \
            not any(_is_traced(d) for d in pdatas):
        # lazy cost capture (mx.profiling): a dict insert keyed on the
        # eager-jit cache sig; lower+compile+parse happens at report
        # time, never here.  LazyData operands are fine -- they carry
        # aval shape/dtype and the store abstracts everything to
        # ShapeDtypeStructs on registration; excluding them made
        # capture depend on whether the dispatch rode the bulk queue,
        # which varies with process-global cache warmth (a test-order
        # flake: a warm FullyConnected cache dropped the second layer's
        # report)
        cargs = ((dyn_vals, key) + tuple(pdatas)) if op.stateful_rng \
            else ((dyn_vals,) + tuple(pdatas))
        _profiling.capture_jit("eager:%s" % op.name, jfn, cargs,
                               key=("eager", sig), kind="eager_jit")

    if out is not None:
        src = result if not isinstance(result, list) else result[0]
        out._buf = src._buf
        out._ag_node = src._ag_node
        out._ag_out_index = src._ag_out_index
        return out
    return result


# ----------------------------------------------------------------------
# Creation functions (reference: init_op.cc + ndarray.py module funcs)
# ----------------------------------------------------------------------

def _resolve_ctx(ctx):
    return ctx if ctx is not None else current_context()


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (reference: ``mx.nd.array``)."""
    if isinstance(source_array, NDArray):
        source_array = source_array.asnumpy()
    arr = np.asarray(source_array)
    if dtype is None:
        dtype = np.float32 if arr.dtype == np.float64 else arr.dtype
    arr = arr.astype(dtype)
    return NDArray(jax.device_put(arr, _resolve_ctx(ctx).jax_device()))


def from_jax(x):
    return NDArray(x)


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype="float32", **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jax.device_put(jnp.zeros(shape, np.dtype(dtype)),
                                  _resolve_ctx(ctx).jax_device()))


def ones(shape, ctx=None, dtype="float32", **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jax.device_put(jnp.ones(shape, np.dtype(dtype)),
                                  _resolve_ctx(ctx).jax_device()))


def full(shape, val, ctx=None, dtype="float32"):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jax.device_put(jnp.full(shape, val, np.dtype(dtype)),
                                  _resolve_ctx(ctx).jax_device()))


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = jnp.arange(start, stop, step, np.dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return NDArray(jax.device_put(out, _resolve_ctx(ctx).jax_device()))


def moveaxis(data, source, destination):
    return NDArray(jnp.moveaxis(data._data, source, destination))


def onehot_encode(indices, out):
    depth = out.shape[-1]
    res = invoke(get_op("one_hot"), [indices], {"depth": depth})
    out._data = res._data
    return out


def concat(*data, dim=1):
    return invoke(get_op("Concat"), list(data), {"dim": dim})


def concatenate(arrays, axis=0):
    return invoke(get_op("Concat"), list(arrays), {"dim": axis})


# ----------------------------------------------------------------------
# Serialization: the reference's .params container
# (reference: src/ndarray/ndarray.cc :: NDArray::Save/Load, magic numbers
# kMXAPINDArrayListMagic=0x112, NDARRAY_V2_MAGIC=0xF993FAC9).  Binary
# layout follows the reference's dmlc::Stream order; exact byte-for-byte
# compatibility could not be verified against the (empty) mount -- the
# format below is self-consistent and documented.
# ----------------------------------------------------------------------

_LIST_MAGIC = 0x112
_ND_MAGIC = 0xF993FAC9


def _save_one(f, arr):
    # accepts host numpy arrays too: the checkpoint subsystem's async
    # writer serializes device_get snapshots off-thread, and wrapping
    # them back into NDArray would round-trip through the device
    a = arr.asnumpy() if isinstance(arr, NDArray) else np.asarray(arr)
    f.write(struct.pack("<I", _ND_MAGIC))
    f.write(struct.pack("<i", 0))  # storage type: dense
    f.write(struct.pack("<I", a.ndim))
    for d in a.shape:
        f.write(struct.pack("<q", d))
    f.write(struct.pack("<ii", 1, 0))  # dev_type=cpu, dev_id
    f.write(struct.pack("<i", _MX_DTYPE_TO_FLAG[np.dtype(a.dtype)]))
    buf = np.ascontiguousarray(a)
    if buf.dtype == np.dtype(jnp.bfloat16.dtype):
        f.write(buf.view(np.uint16).tobytes())
    else:
        f.write(buf.tobytes())


def _load_one(f) -> NDArray:
    magic, = struct.unpack("<I", f.read(4))
    if magic != _ND_MAGIC:
        raise MXNetError("bad NDArray magic 0x%x" % magic)
    struct.unpack("<i", f.read(4))  # stype
    ndim, = struct.unpack("<I", f.read(4))
    shape = tuple(struct.unpack("<q", f.read(8))[0] for _ in range(ndim))
    struct.unpack("<ii", f.read(8))
    flag, = struct.unpack("<i", f.read(4))
    dtype = _FLAG_TO_MX_DTYPE[flag]
    n = int(np.prod(shape)) if shape else 1
    if flag == 100:
        raw = np.frombuffer(f.read(n * 2), dtype=np.uint16).view(
            np.dtype(jnp.bfloat16.dtype))
    else:
        raw = np.frombuffer(f.read(n * dtype.itemsize), dtype=dtype)
    return NDArray(jnp.asarray(raw.reshape(shape)))


def save(fname, data):
    """Save NDArrays (or host numpy arrays) to the reference's
    ``.params`` container format (reference: ``mx.nd.save`` /
    ``c_api.cc :: MXNDArraySave``).

    This is the serialization *primitive*: it writes ``fname`` in
    place.  State-checkpoint callers must wrap it in
    ``mx.checkpoint.core.commit`` for torn-write safety (the
    bare-state-write lint rule enforces this at call sites).
    """
    if isinstance(data, NDArray):
        data, names = [data], []
    elif isinstance(data, dict):
        names = list(data.keys())
        data = [data[k] for k in names]
    else:
        data, names = list(data), []
    with open(fname, "wb") as f:  # mxlint: disable=bare-state-write
        f.write(struct.pack("<Q", _LIST_MAGIC))
        f.write(struct.pack("<Q", 0))
        f.write(struct.pack("<Q", len(data)))
        for arr in data:
            _save_one(f, arr)
        f.write(struct.pack("<Q", len(names)))
        for n in names:
            b = n.encode("utf-8")
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname):
    """Load a ``.params`` container (reference: ``mx.nd.load``)."""
    with open(fname, "rb") as f:
        magic, = struct.unpack("<Q", f.read(8))
        if magic != _LIST_MAGIC:
            raise MXNetError("bad .params magic 0x%x" % magic)
        struct.unpack("<Q", f.read(8))
        count, = struct.unpack("<Q", f.read(8))
        arrays = [_load_one(f) for _ in range(count)]
        nnames, = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(nnames):
            ln, = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode("utf-8"))
    if names:
        return dict(zip(names, arrays))
    return arrays


def transpose(data, axes=None):
    return invoke(get_op("transpose"), [data], {"axes": axes})
