"""Import-time codegen of the ``mx.nd.*`` function surface.

TPU-native analog of the reference's ``python/mxnet/ndarray/register.py ::
_make_ndarray_function``: for every registered op, synthesize a Python
function whose keyword signature and docstring come from the op's typed
parameter list (the dmlc::Parameter parity property).
"""
from __future__ import annotations

import keyword

from ..ops.registry import OP_REGISTRY
from .ndarray import invoke


_UNSET = object()  # sentinel: distinguishes "param not passed" so the
# dispatcher can inject context-dependent defaults (e.g. training mode)


def _make_function(op, pyname):
    params = [p for p in op.params]
    glb = {"_invoke": invoke, "_op": op, "_UNSET": _UNSET}
    arg_bits = []
    if op.variadic:
        arg_bits.append("*data")
        call_args = "list(data)"
    else:
        for a in op.arg_names:
            arg_bits.append("%s=None" % a)
        call_args = "[%s]" % ", ".join(op.arg_names)
    kw_bits = []
    for p in params:
        nm = p.name + ("_" if keyword.iskeyword(p.name) else "")
        kw_bits.append("%s=_UNSET" % nm)
    sig = ", ".join(arg_bits + kw_bits + ["out=None", "name=None", "**kwargs"])
    kw_fill = "\n".join(
        "    if %s is not _UNSET: kwargs[%r] = %s"
        % (p.name + ("_" if keyword.iskeyword(p.name) else ""), p.name,
           p.name + ("_" if keyword.iskeyword(p.name) else ""))
        for p in params)
    src = (
        "def %s(%s):\n"
        "%s\n"
        "    return _invoke(_op, %s, kwargs, out=out)\n"
        % (pyname, sig, kw_fill or "    pass", call_args))
    exec(compile(src, "<mxnet_tpu-op-gen>", "exec"), glb)
    fn = glb[pyname]
    fn.__doc__ = op.doc
    fn.__module__ = "mxnet_tpu.ndarray"
    return fn


def populate(namespace):
    """Generate one function per registered op name into ``namespace``."""
    seen = {}
    for name, op in OP_REGISTRY.items():
        pyname = name if name.isidentifier() else None
        if pyname is None:
            continue
        if pyname in namespace and not callable(namespace.get(pyname)):
            continue
        fn = seen.get(id(op))
        if fn is None:
            fn = _make_function(op, pyname)
            seen[id(op)] = fn
        namespace[pyname] = fn
    return namespace
