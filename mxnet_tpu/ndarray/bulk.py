"""Bulked eager dispatch: the TPU analog of the reference engine's
operator bulking (``MXNET_EXEC_BULK_EXEC_TRAIN`` /
``imperative_runtime.h :: DeferredComputation``).

Eager op calls do not execute one XLA program each; they append to a
process-wide queue of *pending* calls whose outputs are ``LazyData``
placeholders (shape/dtype known from a per-signature aval cache, no
tracing).  At a sync point -- ``asnumpy``/``asscalar``/``waitall``/any
``_data`` read -- the whole pending region is replayed inside ONE jitted
function, so XLA fuses across op boundaries and the host pays one
dispatch instead of N.  The compiled replay program is cached on the
structural key of the region (op signatures + wiring + input avals):
a steady-state training loop compiles its region once and then replays.

Correctness contract: device-side errors surface at the sync point, the
same contract the async dependency engine gives the reference
(``threaded_engine.cc :: WaitToRead``).
"""
from __future__ import annotations

import os

import jax
import numpy as np

__all__ = ["LazyData", "enabled", "enqueue", "flush", "materialize"]

_ENABLED = os.environ.get("MXNET_TPU_EAGER_BULK", "1") != "0"
# capacity flush: bounds host memory for loops that never sync
_MAX_PENDING = int(os.environ.get("MXNET_TPU_EAGER_BULK_MAX", "512"))


def enabled():
    return _ENABLED


class LazyData:
    """Placeholder for the output of a pending bulked op: carries the
    aval (shape/dtype) so shape inference and ndarray properties never
    force execution; ``materialize()`` flushes the queue."""

    __slots__ = ("shape", "dtype", "slot", "_concrete", "device")

    def __init__(self, shape, dtype, slot, device=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.slot = slot
        self.device = device
        self._concrete = None

    @property
    def ndim(self):
        return len(self.shape)

    def materialize(self):
        if self._concrete is None:
            flush()
        if self._concrete is None:
            raise RuntimeError(
                "LazyData %r was not resolved by flush(); its pending "
                "region was lost (a prior flush may have failed)" % self)
        return self._concrete

    def __repr__(self):
        state = "pending" if self._concrete is None else "resolved"
        return "LazyData(%s, %s, %s)" % (self.shape, self.dtype, state)


# -- queue state -------------------------------------------------------

_entries = []          # [(fnc, key_tag, treedef, markers, out_slots, out_treedef)]
_leaf_vals = []        # concrete leaf inputs for the current epoch
_pending = []          # LazyData produced this epoch, slot-ordered
_key_parts = []        # structural key accumulator
_region_dev = None     # device token of the current region (mixed-device
                       # regions would fail to jit as one program)

_AVAL_CACHE = {}       # (key_tag, in_descr) -> (out_treedef, [(shape, dtype)])
_FLUSH_CACHE = {}      # structural key -> jitted replay fn


def _leaf_descr(x):
    if isinstance(x, LazyData):
        return ("lazyaval", x.shape, str(x.dtype))
    if isinstance(x, (jax.Array, np.ndarray)):
        return ("arr", tuple(x.shape), str(x.dtype))
    if isinstance(x, (bool, int, float, complex)):
        return ("py", type(x).__name__)
    return ("obj", type(x).__name__)


def _in_descr(flat):
    return tuple(_leaf_descr(x) for x in flat)


def enqueue(fnc, key_tag, args, device=None):
    """Append a call of ``fnc(*args)`` to the pending region and return
    its outputs as a pytree of LazyData.  ``key_tag`` must uniquely and
    stably identify ``fnc``'s computation (the eager-jit sig).

    Falls back to executing immediately (returning concrete outputs)
    when output avals for this (key_tag, input-aval) pair are not known
    yet -- the warmup call doubles as the aval probe.
    """
    flat, treedef = jax.tree_util.tree_flatten(args)
    descr = _in_descr(flat)
    aval_key = (key_tag, descr)
    cached = _AVAL_CACHE.get(aval_key)
    if cached is None:
        # warmup: run now (also compiles fnc) and record output avals
        out = fnc(*_resolve_args(args))
        oflat, otree = jax.tree_util.tree_flatten(out)
        _AVAL_CACHE[aval_key] = (otree, [(tuple(o.shape), o.dtype)
                                         for o in oflat])
        return out

    # one region = one device: a pending region whose leaves span
    # devices cannot execute as a single jitted program
    global _region_dev
    tok = None
    if device is not None:
        tok = (device,)
    else:
        for x in flat:
            if isinstance(x, jax.Array):
                tok = tuple(sorted(x.devices(), key=lambda d: d.id))
                break
            if isinstance(x, LazyData) and x._concrete is None \
                    and x.device is not None:
                tok = (x.device,)
                break
    if _entries and tok is not None and _region_dev is not None \
            and tok != _region_dev:
        flush()
    if tok is not None and not _entries:
        _region_dev = tok

    out_treedef, out_avals = cached
    markers = []
    for x in flat:
        if isinstance(x, LazyData) and x._concrete is None:
            markers.append(("slot", x.slot))
            if device is None:
                device = x.device
        else:
            if isinstance(x, LazyData):
                x = x._concrete
            markers.append(("leaf", len(_leaf_vals)))
            _leaf_vals.append(x)
    out_slots = []
    outs = []
    for shape, dtype in out_avals:
        slot = len(_pending)
        ld = LazyData(shape, dtype, slot, device=device)
        _pending.append(ld)
        out_slots.append(slot)
        outs.append(ld)
    _entries.append((fnc, treedef, tuple(markers), tuple(out_slots),
                     out_treedef))
    _key_parts.append((key_tag, treedef, tuple(markers), descr))
    if len(_entries) >= _MAX_PENDING:
        flush()
    return jax.tree_util.tree_unflatten(out_treedef, outs)


def _resolve_args(args):
    return jax.tree_util.tree_map(
        lambda x: x.materialize() if isinstance(x, LazyData) else x,
        args, is_leaf=lambda x: isinstance(x, LazyData))


def _build_replay(entries, n_slots):
    def replay(leaf_vals):
        env = [None] * n_slots
        for fnc, treedef, markers, out_slots, _otree in entries:
            flat = [env[i] if kind == "slot" else leaf_vals[i]
                    for kind, i in markers]
            args = jax.tree_util.tree_unflatten(treedef, flat)
            out = fnc(*args)
            oflat, _ = jax.tree_util.tree_flatten(out)
            for s, v in zip(out_slots, oflat):
                env[s] = v
        return env
    return replay


def flush():
    """Execute the pending region as one jitted program and resolve
    every LazyData produced this epoch."""
    global _entries, _leaf_vals, _pending, _key_parts
    if not _entries:
        return
    entries, leaf_vals, pending = _entries, _leaf_vals, _pending
    key = tuple(_key_parts)
    _entries, _leaf_vals, _pending, _key_parts = [], [], [], []
    jrep = _FLUSH_CACHE.get(key)
    if jrep is None:
        jrep = jax.jit(_build_replay(entries, len(pending)))
        _FLUSH_CACHE[key] = jrep
    vals = jrep(leaf_vals)
    for ld, v in zip(pending, vals):
        ld._concrete = v


def materialize(x):
    """Concrete value of ``x`` (a LazyData or anything already real)."""
    if isinstance(x, LazyData):
        return x.materialize()
    return x
