"""Bulked eager dispatch: the TPU analog of the reference engine's
operator bulking (``MXNET_EXEC_BULK_EXEC_TRAIN`` /
``imperative_runtime.h :: DeferredComputation``).

Eager op calls do not execute one XLA program each; they append to a
process-wide queue of *pending* calls whose outputs are ``LazyData``
placeholders (shape/dtype known from a per-signature aval cache, no
tracing).  At a sync point -- ``asnumpy``/``asscalar``/``waitall``/any
``_data`` read -- the whole pending region is replayed inside ONE jitted
function, so XLA fuses across op boundaries and the host pays one
dispatch instead of N.  The compiled replay program is cached on the
structural key of the region (op signatures + wiring + input avals):
a steady-state training loop compiles its region once and then replays.

Correctness contract: device-side errors surface at the sync point, the
same contract the async dependency engine gives the reference
(``threaded_engine.cc :: WaitToRead``).
"""
from __future__ import annotations

import os
import threading

import jax
import numpy as np

from .. import sync as _sync

__all__ = ["LazyData", "enabled", "enqueue", "flush", "materialize",
           "set_bulk_size"]

_ENABLED = os.environ.get("MXNET_TPU_EAGER_BULK", "1") != "0"
# capacity flush: bounds host memory for loops that never sync
_MAX_PENDING = int(os.environ.get("MXNET_TPU_EAGER_BULK_MAX", "512"))


def enabled():
    return _ENABLED


def set_bulk_size(size):
    """Set the capacity-flush threshold (max eager ops per bulked
    region); returns the previous effective size (0 when bulking was
    off).  ``size <= 1`` disables bulking after flushing any pending
    region -- the runtime control surface behind
    ``mx.engine.set_bulk_size`` / ``mx.engine.bulk`` (reference:
    ``MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN``)."""
    global _ENABLED, _MAX_PENDING
    size = int(size)
    with _LOCK:
        prev = _MAX_PENDING if _ENABLED else 0
        if size <= 1:
            _ENABLED = False
        else:
            _ENABLED = True
            _MAX_PENDING = size
    if size <= 1:
        flush()
    return prev


class LazyData:
    """Placeholder for the output of a pending bulked op: carries the
    aval (shape/dtype) so shape inference and ndarray properties never
    force execution; ``materialize()`` flushes the queue.

    If the op that produces this value failed during flush, the
    exception is captured on ``_error`` and re-raised at every read --
    the reference's captured-exception contract
    (``threaded_engine.cc :: OnCompleteStatic``)."""

    __slots__ = ("shape", "dtype", "slot", "_concrete", "device",
                 "_error", "_region")

    def __init__(self, shape, dtype, slot, device=None, region=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.slot = slot
        self.device = device
        self._concrete = None
        self._error = None
        self._region = region

    @property
    def ndim(self):
        return len(self.shape)

    def materialize(self):
        if self._concrete is None and self._error is None:
            flush()
            if self._concrete is None and self._error is None \
                    and self._region is not None:
                # our region was swapped out by another thread's flush
                # and is executing there; wait for its completion event
                # (set in flush's finally, so this can't hang on a
                # failed replay)
                self._region.done.wait()
        if self._error is not None:
            raise self._error
        if self._concrete is None:
            raise RuntimeError(
                "LazyData %r was not resolved by flush(); its pending "
                "region was lost (a prior flush may have failed)" % self)
        return self._concrete

    def __repr__(self):
        state = "failed" if self._error is not None else \
            ("pending" if self._concrete is None else "resolved")
        return "LazyData(%s, %s, %s)" % (self.shape, self.dtype, state)


class _Region:
    """Identity + completion event for one pending region: enqueue only
    slot-wires LazyData belonging to the CURRENT region; readers of a
    region being executed by another thread wait on ``done``."""

    __slots__ = ("done",)

    def __init__(self):
        self.done = threading.Event()


# -- queue state -------------------------------------------------------
# One process-wide region guarded by _LOCK: any thread may enqueue
# (DataLoader workers touching mx.nd, Horovod callbacks) and any thread
# may flush (a cross-thread materialize of a handed-off NDArray).  The
# RLock makes that safe -- enqueue's warmup path can recursively flush
# on the same thread.  Ops from different threads may interleave in one
# region; replay respects the slot-level data dependencies, and eager
# ops are pure, so interleaving only affects the structural key.

_LOCK = _sync.RLock(name="bulk.region")

_entries = []          # [(fnc, key_tag, treedef, markers, out_slots, out_treedef)]
_leaf_vals = []        # concrete leaf inputs for the current epoch
_pending = []          # LazyData produced this epoch, slot-ordered
_key_parts = []        # structural key accumulator
_region_dev = None     # device token of the current region (mixed-device
                       # regions would fail to jit as one program)
_cur_region = _Region()

_AVAL_CACHE = {}       # (key_tag, in_descr) -> (out_treedef, [(shape, dtype)])
_FLUSH_CACHE = {}      # structural key -> jitted replay fn
# programs with data-dependent sync points generate unbounded distinct
# region keys; bound both caches with FIFO eviction (an evicted aval
# entry just re-warms; an evicted replay fn just re-jits)
_CACHE_MAX = 1024
# sentinel for region keys whose jitted replay failed deterministically:
# later flushes of the same key skip the (expensive) re-trace attempt
# and go straight to the eager fallback
_FAILED = object()


def _cache_put(cache, key, val):
    if len(cache) >= _CACHE_MAX:
        cache.pop(next(iter(cache)))
    cache[key] = val


def _leaf_descr(x):
    if isinstance(x, LazyData):
        return ("lazyaval", x.shape, str(x.dtype))
    if isinstance(x, (jax.Array, np.ndarray)):
        return ("arr", tuple(x.shape), str(x.dtype))
    if isinstance(x, (bool, int, float, complex)):
        return ("py", type(x).__name__)
    return ("obj", type(x).__name__)


def _in_descr(flat):
    return tuple(_leaf_descr(x) for x in flat)


def _stale(x):
    """A pending input unusable as a slot wire: it failed in a prior
    flush (must re-raise ITS error, not wire a stale slot index into
    this region) or it belongs to a region another thread swapped out
    and is executing."""
    return (isinstance(x, LazyData) and x._concrete is None
            and (x._error is not None or x._region is not _cur_region))


def enqueue(fnc, key_tag, args, device=None):
    """Append a call of ``fnc(*args)`` to the pending region and return
    its outputs as a pytree of LazyData.  ``key_tag`` must uniquely and
    stably identify ``fnc``'s computation (the eager-jit sig).

    Falls back to executing immediately (returning concrete outputs)
    when output avals for this (key_tag, input-aval) pair are not known
    yet -- the warmup call doubles as the aval probe.
    """
    flat, treedef = jax.tree_util.tree_flatten(args)
    while True:
        # Stale inputs are materialized OUTSIDE the global lock:
        # materialize may wait on another region's in-flight execution
        # (its ``done`` event) or replay a failed region, and doing
        # that under _LOCK would serialize every thread's eager
        # dispatch behind one region's device time.  The scan retries
        # under the lock below -- a flush racing between the two scans
        # can only mint NEW stale entries, which the retry resolves.
        if any(_stale(x) for x in flat):
            flat = [x.materialize() if _stale(x) else x for x in flat]
        with _LOCK:
            if any(_stale(x) for x in flat):
                continue               # a racing flush; resolve again
            result, need_flush = _enqueue_locked(fnc, key_tag, flat,
                                                 treedef, device)
            break
    # the capacity flush (the NORMAL flush trigger for long loops) runs
    # outside the lock so its region execution doesn't serialize other
    # threads' eager dispatch
    if need_flush:
        flush()
    return result


def _enqueue_locked(fnc, key_tag, flat, treedef, device):
    """Wire one call into the current region; caller holds ``_LOCK``
    and has resolved every stale input."""
    # resolved LazyData are plain concrete leaves from here on; the
    # region-key descr is computed AFTER that normalization, so a
    # replayed region keys identically whether an input arrived
    # concrete or as an already-resolved placeholder
    flat = [x._concrete if isinstance(x, LazyData)
            and x._concrete is not None else x for x in flat]
    descr = _in_descr(flat)
    aval_key = (key_tag, descr)
    cached = _AVAL_CACHE.get(aval_key)
    if cached is None:
        # warmup: run now (also compiles fnc) and record output avals;
        # remaining LazyData belong to the current region and resolve
        # via the recursive flush (RLock)
        args = jax.tree_util.tree_unflatten(treedef, flat)
        out = fnc(*_resolve_args(args))
        oflat, otree = jax.tree_util.tree_flatten(out)
        _cache_put(_AVAL_CACHE, aval_key,
                   (otree, [(tuple(o.shape), o.dtype) for o in oflat]))
        return out, False

    # one region = one device: a pending region whose leaves span
    # devices cannot execute as a single jitted program
    global _region_dev
    tok = None
    if device is not None:
        tok = (device,)
    else:
        for x in flat:
            if isinstance(x, jax.Array):
                tok = tuple(sorted(x.devices(), key=lambda d: d.id))
                break
            if isinstance(x, LazyData) and x._concrete is None \
                    and x.device is not None:
                tok = (x.device,)
                break
    if _entries and tok is not None and _region_dev is not None \
            and tok != _region_dev:
        flush()
    if tok is not None and not _entries:
        _region_dev = tok

    out_treedef, out_avals = cached
    markers = []
    for x in flat:
        if isinstance(x, LazyData) and x._concrete is None:
            markers.append(("slot", x.slot))
            if device is None:
                device = x.device
        else:
            markers.append(("leaf", len(_leaf_vals)))
            _leaf_vals.append(x)
    out_slots = []
    outs = []
    for shape, dtype in out_avals:
        slot = len(_pending)
        ld = LazyData(shape, dtype, slot, device=device,
                      region=_cur_region)
        _pending.append(ld)
        out_slots.append(slot)
        outs.append(ld)
    _entries.append((fnc, treedef, tuple(markers), tuple(out_slots),
                     out_treedef))
    _key_parts.append((key_tag, treedef, tuple(markers), descr))
    need_flush = len(_entries) >= _MAX_PENDING
    return jax.tree_util.tree_unflatten(out_treedef, outs), need_flush


def _resolve_args(args):
    return jax.tree_util.tree_map(
        lambda x: x.materialize() if isinstance(x, LazyData) else x,
        args, is_leaf=lambda x: isinstance(x, LazyData))


def _build_replay(entries, n_slots):
    def replay(leaf_vals):
        env = [None] * n_slots
        for fnc, treedef, markers, out_slots, _otree in entries:
            flat = [env[i] if kind == "slot" else leaf_vals[i]
                    for kind, i in markers]
            args = jax.tree_util.tree_unflatten(treedef, flat)
            out = fnc(*args)
            oflat, _ = jax.tree_util.tree_flatten(out)
            for s, v in zip(out_slots, oflat):
                env[s] = v
        return env
    return replay


def _replay_eager(entries, leaf_vals, n_slots):
    """Un-jitted op-by-op replay, used when the jitted replay fails:
    the failing op raises its OWN error; ops not downstream of it still
    resolve; downstream ops inherit the upstream exception."""
    env = [None] * n_slots
    errs = [None] * n_slots
    first_err = None
    for fnc, treedef, markers, out_slots, _otree in entries:
        up_err = None
        flat = []
        for kind, i in markers:
            if kind == "slot":
                if errs[i] is not None and up_err is None:
                    up_err = errs[i]
                flat.append(env[i])
            else:
                flat.append(leaf_vals[i])
        if up_err is None:
            try:
                out = fnc(*jax.tree_util.tree_unflatten(treedef, flat))
                oflat, _ = jax.tree_util.tree_flatten(out)
                for s, v in zip(out_slots, oflat):
                    env[s] = v
                continue
            except Exception as e:   # noqa: BLE001 -- captured contract
                up_err = e
                if first_err is None:
                    first_err = e
        for s in out_slots:
            errs[s] = up_err
    return env, errs, first_err


def flush():
    """Execute the pending region as one jitted program and resolve
    every LazyData produced this epoch."""
    global _entries, _leaf_vals, _pending, _key_parts, _region_dev, \
        _cur_region
    with _LOCK:
        if not _entries:
            return
        entries, leaf_vals, pending = _entries, _leaf_vals, _pending
        key = tuple(_key_parts)
        reg = _cur_region
        _entries, _leaf_vals, _pending, _key_parts = [], [], [], []
        _region_dev = None
        _cur_region = _Region()
        jrep = _FLUSH_CACHE.get(key)
        fresh = jrep is None
        if fresh:
            # jax.jit construction is lazy -- trace/compile happen at
            # the call below, OUTSIDE the lock
            jrep = jax.jit(_build_replay(entries, len(pending)))
            _cache_put(_FLUSH_CACHE, key, jrep)
    # Execution runs outside the lock so other threads keep enqueueing
    # into the fresh region; cross-thread readers of THIS region's
    # LazyData wait on reg.done (see materialize).  The finally
    # guarantees waiters wake even when the replay fails.
    try:
        vals = None
        if jrep is not _FAILED:
            try:
                vals = jrep(leaf_vals)
            except Exception:
                # Poison the key only when THIS flush created the jit
                # wrapper: a first-call failure is a trace/compile
                # failure that would re-pay the full trace on every
                # flush.  A previously-warm jrep that fails was
                # compiled and ran before -- the failure is transient
                # (device OOM spike) and the key stays jittable.
                # (No lock: CPython dict writes are atomic, and taking
                # _LOCK here could deadlock against an enqueue waiting
                # on reg.done.)
                if fresh:
                    _FLUSH_CACHE[key] = _FAILED
        if vals is not None:
            for ld, v in zip(pending, vals):
                ld._concrete = v
            return
        # The jitted replay failed (compile error, device OOM, a
        # runtime check): fall back to eager replay so the failing
        # op surfaces its own error at THIS sync point and every
        # LazyData not downstream of it still resolves (reference:
        # threaded_engine.cc :: OnCompleteStatic re-throws the
        # captured exception at WaitToRead).
        vals, errs, first_err = _replay_eager(entries, leaf_vals,
                                              len(pending))
        for ld, v, e in zip(pending, vals, errs):
            ld._concrete = v
            ld._error = e
        if first_err is not None:
            raise first_err
        # every op ran clean eagerly, so the jitted failure was
        # transient (first-call OOM spike, compile-service drop): drop
        # the poisoned/failed cache entry so the key re-jits next flush
        _FLUSH_CACHE.pop(key, None)
    finally:
        reg.done.set()


def materialize(x):
    """Concrete value of ``x`` (a LazyData or anything already real)."""
    if isinstance(x, LazyData):
        return x.materialize()
    return x


def materialize_tree(tree):
    """``materialize`` mapped over a pytree, treating LazyData as
    leaves (the shared idiom for making cotangent/operand trees
    concrete before handing them to a raw ``jax.vjp`` pull)."""
    return jax.tree_util.tree_map(
        materialize, tree, is_leaf=lambda x: isinstance(x, LazyData))
