"""Training callbacks (reference: ``python/mxnet/callback.py``).

``Speedometer`` is the reference's canonical throughput logger -- the
driver-visible samples/sec convention all MXNet training scripts share.
Callbacks receive a ``BatchEndParam`` (``model.py``).
"""
from __future__ import annotations

import logging
import time

from . import telemetry as _telemetry
from .model import save_checkpoint


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback checkpointing a Module (reference:
    ``callback.py :: module_checkpoint``)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end callback: ``save_checkpoint(prefix, epoch+1, ...)``
    (reference: ``callback.py :: do_checkpoint``).  Writes are atomic
    (mx.checkpoint commit) since the ISSUE 3 rebase."""
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def managed_checkpoint(manager, period=1, metadata_fn=None):
    """Epoch-end callback saving through a
    :class:`mx.checkpoint.CheckpointManager` -- manifest-verified,
    retention-pruned, optionally async -- instead of bare prefix files.

    ``manager`` owns layout and retention; ``metadata_fn(iter_no)``
    (optional) supplies the manifest's user metadata.
    """
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period != 0:
            return
        items = {}
        if arg:
            items["params"] = {"arg:%s" % k: v for k, v in arg.items()}
            items["params"].update(
                {"aux:%s" % k: v for k, v in (aux or {}).items()})
        if not items:
            return
        meta = metadata_fn(iter_no) if metadata_fn is not None else None
        manager.save(iter_no + 1, items, metadata=meta)
    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the metric every ``period`` batches
    (reference: ``callback.py :: log_train_metric``)."""
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset_local()
    return _callback


class Speedometer:
    """Logs samples/sec every ``frequent`` batches (reference:
    ``callback.py :: Speedometer``).  This is the throughput convention
    bench.py reports against BASELINE.md."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.last_speed = None

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                try:
                    speed = self.frequent * self.batch_size / \
                        (time.time() - self.tic)
                except ZeroDivisionError:
                    speed = float("inf")
                self.last_speed = speed
                if _telemetry._ENABLED:
                    # same gauge Trainer.step feeds: Module-API and
                    # Gluon throughput report through one channel
                    _telemetry.hooks.samples_per_sec(speed)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    msg = "Epoch[%d] Batch [%d-%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(msg, param.epoch, count - self.frequent,
                                 count, speed,
                                 *sum(name_value, ()))
                    if self.auto_reset:
                        param.eval_metric.reset_local()
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """Text progress bar per epoch (reference: ``callback.py ::
    ProgressBar``)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = int(round(100.0 * count / float(self.total)))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


class LogValidationMetricsCallback:
    """Eval-end callback (reference: same name)."""

    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         param.epoch, name, value)
