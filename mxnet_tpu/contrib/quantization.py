"""Post-training int8 quantization workflow (reference:
``python/mxnet/contrib/quantization.py :: quantize_model, calibrate,
_LayerOutputCollector, _get_optimal_threshold``).

Graph-level transform over Symbol DAGs driving the int8 ops in
``ops/contrib_ops.py``: each quantizable node (Convolution /
FullyConnected) becomes ``quantize_v2 -> quantized_op -> dequantize``
with calibrated ranges; weights/biases are pre-quantized into int8
parameter tensors.  Calibration modes follow the reference: ``none``
(runtime min/max), ``naive`` (calibrated min/max over calib batches),
``entropy`` (KL-divergence-optimal thresholds, the TensorRT method the
reference implements in ``_get_optimal_threshold``).

TPU note: int8 contractions accumulate in int32 on the MXU
(``preferred_element_type``), so the simulated-quantization graphs here
run at native int8 matmul speed under jit.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["quantize_model", "calibrate", "quantize_graph",
           "QUANTIZABLE_OPS"]

QUANTIZABLE_OPS = ("Convolution", "FullyConnected")


# ----------------------------------------------------------------------
# Calibration
# ----------------------------------------------------------------------

def _optimal_threshold_entropy(arr, num_bins=2048, num_quantized_bins=128):
    """KL-divergence-optimal |threshold| for int8 (reference:
    ``_get_optimal_threshold``)."""
    a = np.abs(np.asarray(arr, np.float64)).ravel()
    amax = a.max() if a.size else 0.0
    if amax == 0.0:
        return 1e-8
    hist, edges = np.histogram(a, bins=num_bins, range=(0.0, amax))
    best_kl = np.inf
    best_t = amax
    total = hist.sum()
    if total == 0:
        return float(amax)
    # candidate thresholds must keep >= 99% of the mass un-clipped: with
    # small calibration sets the histogram is sparse and an unconstrained
    # KL scan can collapse onto a tiny threshold (the reference gets away
    # without this because its calib sets are full batches of real data)
    cum = np.cumsum(hist)
    start = int(np.searchsorted(cum, 0.99 * total)) + 1
    start = max(num_quantized_bins, start)
    for i in range(start, num_bins + 1,
                   max(1, num_bins // 128)):
        ref = hist[:i].astype(np.float64).copy()
        # everything beyond the threshold clips into the last bin
        ref[-1] += hist[i:].sum()
        if ref.sum() == 0:
            continue
        # quantize the i bins down to num_quantized_bins
        chunks = np.array_split(ref, num_quantized_bins)
        q = np.zeros(i, np.float64)
        pos = 0
        for ch in chunks:
            nz = ch > 0
            if nz.any():
                q[pos:pos + len(ch)][nz] = ch.sum() / nz.sum()
            pos += len(ch)
        p = ref / ref.sum()
        qn = q / q.sum() if q.sum() else q
        mask = p > 0
        # smoothed KL(P || Q)
        kl = float(np.sum(p[mask] * np.log(
            p[mask] / np.maximum(qn[mask], 1e-12))))
        if kl < best_kl:
            best_kl = kl
            best_t = edges[i]
    return float(best_t)


def calibrate(sym, arg_params, aux_params, calib_data,
              data_names=("data",), calib_mode="entropy",
              num_calib_batches=None, quantizable_ops=QUANTIZABLE_OPS,
              excluded_sym_names=()):
    """Collect per-tensor thresholds for every quantizable node input.

    ``calib_data`` yields batches: arrays / NDArrays (single input) or
    dicts of them.  Returns ``{tensor_name: (min, max)}`` covering each
    quantizable node's data input.  Reference:
    ``quantization.py :: calibrate / _collect_layer_statistics``.
    """
    from .. import ndarray as nd
    from ..symbol.symbol import Group, Symbol

    # tensors to observe: the data input of every quantizable node
    nodes = [n for n in sym._topo()
             if n.op in quantizable_ops and n.name not in excluded_sym_names]
    watch = []  # (tensor_name, Symbol) pairs
    seen = set()
    for node in nodes:
        src, idx = node.inputs[0]
        tname = src.name if idx == 0 else "%s_out%d" % (src.name, idx)
        if tname in seen:
            continue
        seen.add(tname)
        watch.append((tname, Symbol([(src, idx)])))
    if not watch:
        return {}
    group = Group([s for _, s in watch])

    stats = {name: [] for name, _ in watch}
    consts = dict(arg_params)
    consts.update(aux_params)
    n_done = 0
    for batch in calib_data:
        if num_calib_batches is not None and n_done >= num_calib_batches:
            break
        n_done += 1
        feeds = dict(consts)
        if isinstance(batch, dict):
            feeds.update({k: nd.array(np.asarray(v)) if not isinstance(
                v, nd.NDArray) else v for k, v in batch.items()})
        else:
            if not isinstance(batch, nd.NDArray):
                batch = nd.array(np.asarray(batch))
            feeds[data_names[0]] = batch
        outs = group.eval(**feeds)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        for (name, _), val in zip(watch, outs):
            stats[name].append(val.asnumpy())

    thresholds = {}
    for name, chunks in stats.items():
        if not chunks:
            raise MXNetError("calibrate: calib_data yielded no batches")
        allv = np.concatenate([c.ravel() for c in chunks])
        if calib_mode == "naive":
            t = float(np.max(np.abs(allv))) or 1e-8
        elif calib_mode == "entropy":
            t = _optimal_threshold_entropy(allv)
        else:
            raise MXNetError("calibrate: unknown calib_mode %r"
                             % calib_mode)
        thresholds[name] = (-t, t)
    return thresholds


# ----------------------------------------------------------------------
# Graph transform
# ----------------------------------------------------------------------

def _quantize_weight(arr):
    a = np.asarray(arr, np.float32)
    bound = float(np.max(np.abs(a))) or 1e-8
    q = np.clip(np.round(a * (127.0 / bound)), -127, 127).astype(np.int8)
    return q, bound


def quantize_graph(sym, arg_params, aux_params, thresholds=None,
                   excluded_sym_names=(), quantizable_ops=QUANTIZABLE_OPS):
    """Rewrite a fp32 Symbol into an int8-compute graph.

    Every quantizable node becomes ``quantize_v2(data) -> quantized_op ->
    dequantize``; weights/biases are quantized offline into the returned
    parameter dict (int8 payload + baked scales).  Non-quantized nodes
    are rebuilt unchanged.  Returns ``(qsym, qarg_params, aux_params)``.
    """
    from ..symbol.symbol import Group, Symbol, _make_node, var

    thresholds = thresholds or {}
    qargs = {k: v for k, v in arg_params.items()}
    env = {}  # id(old_node) -> list of Symbols per output index

    # params still referenced by nodes that STAY fp32 (excluded or
    # non-quantizable) must keep their fp32 entry even when a quantized
    # node shares them (weight tying)
    fp32_referenced = set()
    for n in sym._topo():
        if n.op is None:
            continue
        stays_fp32 = n.op not in quantizable_ops \
            or n.name in excluded_sym_names
        if stays_fp32:
            for src, _ in n.inputs:
                if src.op is None:
                    fp32_referenced.add(src.name)

    def entry_sym(src, idx):
        return env[id(src)][idx]

    for node in sym._topo():
        if node.op is None:
            env[id(node)] = [Symbol(
                [(type(node)(None, node.name, dict(node.attrs), []), 0)])]
            continue
        ins = [entry_sym(s, i) for s, i in node.inputs]
        if node.op in quantizable_ops \
                and node.name not in excluded_sym_names:
            src, idx = node.inputs[0]
            tname = src.name if idx == 0 else \
                "%s_out%d" % (src.name, idx)
            wname = node.inputs[1][0].name
            bname = node.inputs[2][0].name if len(node.inputs) > 2 else None

            # offline weight quantization (idempotent: a weight shared by
            # several quantized nodes is converted once; one also shared
            # with an fp32 node keeps its fp32 entry)
            if wname not in arg_params:
                raise MXNetError("quantize_graph: missing weight param %r"
                                 % wname)
            from .. import ndarray as nd
            if wname + "_quantized" not in qargs:
                qw, wbound = _quantize_weight(arg_params[wname].asnumpy())
                qargs[wname + "_quantized"] = nd.array(qw)
                qargs[wname + "_min"] = nd.array(
                    np.asarray(-wbound, np.float32))
                qargs[wname + "_max"] = nd.array(
                    np.asarray(wbound, np.float32))
                if wname not in fp32_referenced:
                    del qargs[wname]
            w_q = var(wname + "_quantized")
            w_min = var(wname + "_min")
            w_max = var(wname + "_max")

            qparams = {}
            if tname in thresholds:
                lo, hi = thresholds[tname]
                qparams = {"min_calib_range": float(lo),
                           "max_calib_range": float(hi)}
            q_data = _make_node("quantize_v2", [ins[0]], qparams,
                                name=node.name + "_quantize")
            d_q, d_min, d_max = q_data[0], q_data[1], q_data[2]

            op_params = {k: v for k, v in node.attrs.items()}
            no_bias = bname is None
            if no_bias:
                # quantized ops take a full arg list; feed zero-range bias
                b_q = var(node.name + "_nobias")
                b_min = var(node.name + "_nobias_min")
                b_max = var(node.name + "_nobias_max")
                qargs[node.name + "_nobias"] = nd.array(
                    np.zeros((1,), np.int8))
                qargs[node.name + "_nobias_min"] = nd.array(
                    np.asarray(0.0, np.float32))
                qargs[node.name + "_nobias_max"] = nd.array(
                    np.asarray(0.0, np.float32))
                op_params["no_bias"] = True
            else:
                if bname + "_quantized" not in qargs:
                    qb, bbound = _quantize_weight(
                        arg_params[bname].asnumpy())
                    qargs[bname + "_quantized"] = nd.array(qb)
                    qargs[bname + "_min"] = nd.array(
                        np.asarray(-bbound, np.float32))
                    qargs[bname + "_max"] = nd.array(
                        np.asarray(bbound, np.float32))
                    if bname not in fp32_referenced:
                        del qargs[bname]
                b_q = var(bname + "_quantized")
                b_min = var(bname + "_min")
                b_max = var(bname + "_max")
                op_params["no_bias"] = False

            qop = "quantized_conv" if node.op == "Convolution" \
                else "quantized_fully_connected"
            acc = _make_node(qop,
                             [d_q, w_q, b_q, d_min, d_max, w_min, w_max,
                              b_min, b_max],
                             op_params, name=node.name + "_quantized")
            out = _make_node("dequantize", [acc[0], acc[1], acc[2]], {},
                             name=node.name)
            env[id(node)] = [out]
            continue
        # pass through unchanged (rebuild on the new inputs)
        rebuilt = _make_node(node.op, ins, dict(node.attrs),
                             name=node.name)
        env[id(node)] = [rebuilt[i] for i in range(len(rebuilt))] \
            if len(rebuilt) > 1 else [rebuilt]

    outs = [entry_sym(n, i) for n, i in sym._outputs]
    qsym = outs[0] if len(outs) == 1 else Group(outs)
    return qsym, qargs, dict(aux_params)


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   excluded_sym_names=(), calib_mode="entropy",
                   calib_data=None, num_calib_batches=None,
                   quantized_dtype="int8", logger=None, **kwargs):
    """One-call post-training quantization (reference:
    ``mx.contrib.quantization.quantize_model``).

    calib_mode ``none`` bakes no ranges (runtime min/max), ``naive`` and
    ``entropy`` calibrate thresholds from ``calib_data``.  Returns
    ``(qsym, qarg_params, aux_params)``.
    """
    if quantized_dtype != "int8":
        raise MXNetError("quantize_model: only int8 is supported")
    thresholds = {}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError("quantize_model: calib_mode %r needs "
                             "calib_data" % calib_mode)
        thresholds = calibrate(
            sym, arg_params, aux_params, calib_data,
            data_names=data_names, calib_mode=calib_mode,
            num_calib_batches=num_calib_batches,
            excluded_sym_names=excluded_sym_names)
        if logger:
            logger.info("calibrated %d tensors", len(thresholds))
    return quantize_graph(sym, arg_params, aux_params, thresholds,
                          excluded_sym_names=excluded_sym_names)
