"""Weight initializers (reference: ``python/mxnet/initializer.py``).

String-registered initializer classes; ``InitDesc`` carries per-parameter
attribute overrides, matching the reference's serialization of initializer
choice into Parameter definitions.
"""
from __future__ import annotations

import json
import math

import numpy as np

from .base import MXNetError
from . import ndarray as nd

_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if name is None:
        return Uniform()
    key = str(name).lower()
    key = {"zeros": "zero", "ones": "one", "gaussian": "normal"}.get(key, key)
    if key not in _INIT_REGISTRY:
        raise MXNetError("unknown initializer %r" % name)
    return _INIT_REGISTRY[key](**kwargs)


class InitDesc(str):
    """Parameter name + attrs hint passed to initializers (reference:
    ``initializer.py :: InitDesc``)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer; callable on (name, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init_name = desc.attrs.get("__init__", "")
        if init_name:
            create(json.loads(init_name)[0] if init_name.startswith("[")
                   else init_name)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        else:
            self._init_weight(desc, arr)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __repr__(self):
        return "%s(%r)" % (self.__class__.__name__, self._kwargs)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1, 1, (nout, nin))
        else:
            tmp = np.random.normal(0, 1, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@register
class Xavier(Initializer):
    """Reference: ``initializer.py :: Xavier`` (the Gluon default family)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, _, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError("Xavier requires >=2D weight, got %s" % (shape,))
        if len(shape) > 2:
            hw_scale = float(np.prod(shape[2:]))
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("bad factor_type %r" % self.factor_type)
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape)
        else:
            arr[:] = np.random.normal(0, scale, shape)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        Initializer.__init__(self, factor_type=factor_type, slope=slope)
        self.rnd_type = "gaussian"
        self.factor_type = factor_type
        self.magnitude = magnitude


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        weight = np.zeros(arr.shape, dtype=np.float32)
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias 1.0 (reference: ``initializer.py :: LSTMBias``)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, _, arr):
        a = np.zeros(arr.shape, np.float32)
        n = arr.shape[0] // 4
        a[n:2 * n] = self.forget_bias  # gate order i,f,g,o
        arr[:] = a


class Mixed:
    """Pattern->initializer dispatch (reference: ``Mixed``)."""

    def __init__(self, patterns, initializers):
        import re
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for regex, init in self.map:
            if regex.match(name):
                init(name, arr)
                return
        raise MXNetError("no initializer pattern matches %r" % name)
