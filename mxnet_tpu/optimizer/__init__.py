"""``mx.optimizer`` (reference: ``python/mxnet/optimizer/``)."""
from .optimizer import (SGD, NAG, Adam, AdamW, RMSProp, AdaGrad, Ftrl, LAMB,
                        LARS, Signum, Optimizer, Updater, create, get_updater,
                        register)
from . import lr_scheduler
from .lr_scheduler import (CosineScheduler, FactorScheduler, LRScheduler,
                           MultiFactorScheduler, PolyScheduler)
