"""Optimizers (reference: ``python/mxnet/optimizer/optimizer.py``).

Each optimizer's ``update`` dispatches to the fused update ops in
``ops/optimizer_ops.py`` (the reference's ``src/operator/optimizer_op.cc``
kernels).  Functional rebinding replaces in-place mutation: the returned
weight/state arrays are written back into the caller's NDArrays, so under a
compiled trainer step the whole update fuses into one XLA program.
"""
from __future__ import annotations

import pickle

import jax.numpy as jnp

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray

_OPT_REGISTRY = {}


def register(klass):
    # loud on duplicates: two same-named definitions silently
    # overwriting each other is exactly how the host-syncing LARS copy
    # shadowed the trace-safe one for five PRs
    key = klass.__name__.lower()
    if key in _OPT_REGISTRY and _OPT_REGISTRY[key] is not klass:
        raise MXNetError("duplicate optimizer registration %r "
                         "(already %r)" % (key, _OPT_REGISTRY[key]))
    _OPT_REGISTRY[key] = klass
    return klass


class Optimizer:
    """Base optimizer (reference: ``Optimizer`` + ``create``)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 multi_precision=False, param_dict=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count = {}
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    @staticmethod
    def create_optimizer(name, **kwargs):
        key = name.lower()
        if key not in _OPT_REGISTRY:
            raise MXNetError("unknown optimizer %r" % name)
        return _OPT_REGISTRY[key](**kwargs)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == np.float16:
            w32 = weight.astype(np.float32)
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == np.float16:
            inner_state, w32 = state
            g32 = grad.astype(np.float32)
            self.update(index, w32, g32, inner_state)
            weight._data = w32.astype(np.float16)._data
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            lr *= self.param_dict[name].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            wd *= self.param_dict[name].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif name in self.wd_mult:
            wd *= self.wd_mult[name]
        return wd

    def _common_kwargs(self, index):
        kw = {"lr": self._get_lr(index), "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    def _sparse_grad_prep(self, index, grad, weight_rows, fold_wd=True):
        """Scaled/clipped row gradient; with ``fold_wd`` the per-row
        weight-decay term is folded in (matching the dense
        ``_apply_wd`` kernel).  AdaGrad keeps wd OUT of the squared
        history, so it passes ``fold_wd=False``."""
        g = grad.data._data * self.rescale_grad
        if self.clip_gradient is not None and self.clip_gradient > 0:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        if fold_wd:
            wd = self._get_wd(index)
            if wd:
                g = g + wd * weight_rows
        return g

    def update_row_sparse(self, index, weight, grad, state):
        """Row-sparse gradient update touching only the live rows
        (reference: the sparse sgd/adagrad kernels in
        ``src/operator/optimizer_op.cc``).  Default: densify -- correct
        for every optimizer; SGD/AdaGrad override with real row updates.
        """
        self.update(index, weight, grad.todense(), state)

    def update_row_sparse_multi_precision(self, index, weight, grad,
                                          state):
        """Sparse entry point honoring the fp32-master-copy contract:
        with multi-precision active the state is (mom, w32)-style and the
        master copy must stay in sync, so the update runs through the
        dense multi-precision path."""
        if self.multi_precision and weight.dtype == np.float16:
            self.update_multi_precision(index, weight, grad.todense(),
                                        state)
        else:
            self.update_row_sparse(index, weight, grad, state)


def create(name, **kwargs):
    return Optimizer.create_optimizer(name, **kwargs)


@register
class SGD(Optimizer):
    """SGD with momentum and multi-precision (reference: ``SGD``)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None:
            w, m = nd.sgd_mom_update(weight, grad, state,
                                     momentum=self.momentum, **kw)
            weight._data, state._data = w._data, m._data
        else:
            weight._data = nd.sgd_update(weight, grad, **kw)._data

    def update_row_sparse(self, index, weight, grad, state):
        """Lazy row update (reference: sparse ``sgd_update`` with
        ``lazy_update=True``): only rows with gradient move; with
        momentum the reference semantics require the full-state update,
        so it densifies."""
        if self.momentum != 0.0:
            return super().update_row_sparse(index, weight, grad, state)
        self._update_count(index)
        lr = self._get_lr(index)
        rows = grad.indices._data
        g = self._sparse_grad_prep(index, grad, weight._data[rows])
        weight._data = weight._data.at[rows].add(
            (-lr * g).astype(weight._data.dtype))

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == np.float16:
            g = grad
            if self.momentum != 0.0:
                mom, w32 = state[0], state[1]
                w, m, nw32 = nd.mp_sgd_mom_update(
                    weight, g, mom, w32, momentum=self.momentum,
                    **self._common_kwargs(index))
                self._update_count(index)
                weight._data, mom._data, w32._data = w._data, m._data, nw32._data
            else:
                _, w32 = state
                w, nw32 = nd.mp_sgd_update(weight, g, w32,
                                           **self._common_kwargs(index))
                self._update_count(index)
                weight._data, w32._data = w._data, nw32._data
        else:
            self.update(index, weight, grad, state)

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == np.float16:
            w32 = weight.astype(np.float32)
            mom = nd.zeros(weight.shape, ctx=weight.context) \
                if self.momentum != 0.0 else None
            return (mom, w32)
        return self.create_state(index, weight)


@register
class LARS(Optimizer):
    """Layer-wise Adaptive Rate Scaling for large-batch SGD (reference:
    ``optimizer/contrib :: LARS``; BASELINE config 5).  Dispatches to the
    fused ``lars_update`` op (trust ratio + momentum step in ONE traced
    program) -- the trust ratio never leaves the device, so the update is
    trace-safe inside ``jit``/``TrainStep`` (no host-syncing
    ``.asscalar()``: the former second definition of this class computed
    the ratio on the host and raised ``TracerArrayConversionError``
    under trace; it is gone, and ``opt.create('lars')`` is pinned to
    this implementation by test)."""

    def __init__(self, momentum=0.9, eta=0.001, epsilon=1e-9,
                 skip_list=("bias", "gamma", "beta"), **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon
        self.skip_list = tuple(skip_list)

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def _skip_lars(self, index):
        # The reference excludes biases and norm-layer scales from the
        # trust-ratio adaptation (their norms are tiny and unstable).
        p = self.param_dict.get(index)
        name = p.name if p is not None else str(self.idx2name.get(index, ""))
        return name.endswith(self.skip_list)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if self._skip_lars(index):
            w, m = nd.sgd_mom_update(weight, grad, state,
                                     momentum=self.momentum, **kw)
        else:
            w, m = nd.lars_update(weight, grad, state, momentum=self.momentum,
                                  eta=self.eta, epsilon=self.epsilon, **kw)
        weight._data, state._data = w._data, m._data


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference: ``NAG``)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None:
            w, m = nd.nag_mom_update(weight, grad, state,
                                     momentum=self.momentum, **kw)
            weight._data, state._data = w._data, m._data
        else:
            weight._data = nd.sgd_update(weight, grad, **kw)._data


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        # bias correction folded into lr as the reference does
        kw["lr"] *= (1.0 - self.beta2 ** t) ** 0.5 / (1.0 - self.beta1 ** t)
        mean, var = state
        w, m, v = nd.adam_update(weight, grad, mean, var, beta1=self.beta1,
                                 beta2=self.beta2, epsilon=self.epsilon, **kw)
        weight._data, mean._data, var._data = w._data, m._data, v._data


@register
class AdamW(Adam):
    """Decoupled weight decay (reference: ``contrib/optimizer :: AdamW``)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        kw["lr"] *= (1.0 - self.beta2 ** t) ** 0.5 / (1.0 - self.beta1 ** t)
        mean, var = state
        w, m, v = nd.adamw_update(weight, grad, mean, var, beta1=self.beta1,
                                  beta2=self.beta2, epsilon=self.epsilon, **kw)
        weight._data, mean._data, var._data = w._data, m._data, v._data


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)
        if self.centered:
            return (z(), z(), z())
        return z()

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if self.clip_weights is not None:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            w, n2, g2, d2 = nd.rmspropalex_update(
                weight, grad, n, g, delta, gamma1=self.gamma1,
                gamma2=self.gamma2, epsilon=self.epsilon, **kw)
            weight._data, n._data, g._data, delta._data = \
                w._data, n2._data, g2._data, d2._data
        else:
            w, n2 = nd.rmsprop_update(weight, grad, state, gamma1=self.gamma1,
                                      epsilon=self.epsilon, **kw)
            weight._data, state._data = w._data, n2._data


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        w, h = nd.adagrad_update(weight, grad, state,
                                 epsilon=self.float_stable_eps, **kw)
        weight._data, state._data = w._data, h._data

    def update_row_sparse(self, index, weight, grad, state):
        """Sparse AdaGrad (reference: ``_sparse_adagrad_update``): only
        the live rows accumulate history and move.  Same math as the
        dense ``adagrad_update`` kernel: wd stays OUT of the squared
        history, epsilon inside the sqrt."""
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        rows = grad.indices._data
        w_rows = weight._data[rows]
        g = self._sparse_grad_prep(index, grad, w_rows, fold_wd=False)
        h_rows = state._data[rows] + g * g
        state._data = state._data.at[rows].set(h_rows)
        step = g / jnp.sqrt(h_rows + self.float_stable_eps) + wd * w_rows
        weight._data = weight._data.at[rows].add(
            (-lr * step).astype(weight._data.dtype))


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        z, n = state
        kw = self._common_kwargs(index)
        w, z2, n2 = nd.ftrl_update(weight, grad, z, n, lamda1=self.lamda1,
                                   beta=self.beta, **kw)
        weight._data, z._data, n._data = w._data, z2._data, n2._data


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None:
            w, m = nd.signum_update(weight, grad, state, momentum=self.momentum,
                                    wd_lh=self.wd_lh, **kw)
            weight._data, state._data = w._data, m._data
        else:
            weight._data = nd.signsgd_update(weight, grad, **kw)._data


@register
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (reference: ``LAMB``,
    ``optimizer_op.cc :: lamb_update_phase1/2``)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context),
                nd.zeros(weight.shape, dtype=weight.dtype, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        mean, var = state
        kw = {"wd": self._get_wd(index), "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        g, m, v = nd.lamb_update_phase1(weight, grad, mean, var,
                                        beta1=self.beta1, beta2=self.beta2,
                                        epsilon=self.epsilon, t=t,
                                        bias_correction=self.bias_correction,
                                        **kw)
        r1 = weight.norm()
        r2 = g.norm()
        kw2 = {"lr": self._get_lr(index)}
        if self.lower_bound is not None:
            kw2["lower_bound"] = self.lower_bound
        if self.upper_bound is not None:
            kw2["upper_bound"] = self.upper_bound
        w = nd.lamb_update_phase2(weight, g, r1, r2, **kw2)
        weight._data, mean._data, var._data = w._data, m._data, v._data


class Updater:
    """Maps (index, grad, weight) -> state bookkeeping + optimizer.update
    (reference: ``get_updater``/``Updater`` -- the kvstore's server-side
    update callable)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            self.optimizer.update_row_sparse_multi_precision(
                index, weight, grad, self.states[index])
            return
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if isinstance(s, NDArray):
                return ("nd", s.asnumpy())
            if isinstance(s, (tuple, list)):
                return ("tuple", [to_np(x) for x in s])
            return ("raw", s)
        payload = {k: to_np(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((payload, self.optimizer))
        return pickle.dumps(payload)

    def set_states(self, states):
        data = pickle.loads(states)
        if isinstance(data, tuple) and len(data) == 2 and \
                isinstance(data[1], Optimizer):
            payload, self.optimizer = data
        else:
            payload = data

        def from_np(s):
            kind, val = s
            if kind == "nd":
                return nd.array(val)
            if kind == "tuple":
                return tuple(from_np(x) for x in val)
            return val
        self.states = {k: from_np(v) for k, v in payload.items()}


def get_updater(optimizer):
    return Updater(optimizer)
