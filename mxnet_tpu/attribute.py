"""Attribute scoping for symbols (reference: ``python/mxnet/attribute.py
:: AttrScope``): ``with mx.AttrScope(ctx_group='dev1'):`` attaches
attributes to every symbol created in the scope."""
from __future__ import annotations

import threading

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


class AttrScope:
    def __init__(self, **attrs):
        self._attrs = {k: str(v) for k, v in attrs.items()}

    def __enter__(self):
        _stack().append(self._attrs)
        return self

    def __exit__(self, *args):
        _stack().pop()

    @staticmethod
    def current_attrs():
        merged = {}
        for frame in _stack():
            merged.update(frame)
        return merged
