"""Typed registry of every ``MXNET_*`` environment variable the
framework reads (reference: ``docs/static_site/src/pages/api/faq/
env_var.md`` -- the reference documents its env vars on one page; here
the page is generated from this registry, so it cannot go stale).

Use ``mx.env.describe()`` for the rendered table, ``mx.env.get(name)``
for a typed read, and ``mx.env.generate_doc(path)`` to (re)write
``docs/env_vars.md``.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable

from .base import MXNetError

__all__ = ["EnvVar", "REGISTRY", "get", "describe", "generate_doc"]


@dataclass(frozen=True)
class EnvVar:
    name: str
    type: Callable
    default: Any
    doc: str

    def read(self):
        raw = os.environ.get(self.name)
        if raw is None:
            return self.default
        try:
            if self.type is bool:
                # match the package's actual read convention: every
                # boolean site tests != "0" (see e.g. ndarray.py's
                # MXNET_TPU_EAGER_JIT), so only "0" disables
                return raw != "0"
            return self.type(raw)
        except (TypeError, ValueError) as e:
            raise MXNetError("env var %s=%r is not a valid %s"
                             % (self.name, raw, self.type.__name__)) from e


_VARS = [
    EnvVar("MXNET_ENGINE_TYPE", str, "",
           "Set to 'NaiveEngine' to make every op dispatch block until "
           "the result is ready (reference semantics: synchronous debug "
           "engine).  Default: async XLA dispatch."),
    EnvVar("MXNET_TPU_EAGER_JIT", bool, True,
           "Per-op persistent jit cache for eager NDArray ops.  '0' "
           "falls back to uncached dispatch (debugging)."),
    EnvVar("MXNET_TPU_COMPILATION_CACHE", bool, True,
           "Persist compiled XLA programs to disk so later processes "
           "start hot (the reference's analog is cuDNN autotune "
           "caching).  '0' disables."),
    EnvVar("MXNET_TPU_COMPILATION_CACHE_DIR", str,
           "~/.cache/mxnet_tpu/xla/<fingerprint>",
           "Directory for the persistent compilation cache.  When unset, "
           "a per-build subdirectory of ~/.cache/mxnet_tpu/xla keyed on "
           "the jax/jaxlib/libtpu versions and host CPU model+flags is "
           "used, so a home directory shared across machines or compiler "
           "upgrades never serves stale AOT executables (SIGILL / "
           "libtpu-version-mismatch hazard).  Setting the var explicitly "
           "bypasses the fingerprinting."),
    EnvVar("MXNET_TPU_NATIVE", bool, True,
           "Build/load the native C++ components (recordio engine, "
           "predict runtime).  '0' forces the pure-Python paths."),
    EnvVar("MXNET_TPU_NATIVE_CACHE", str, "~/.cache/mxnet_tpu/native",
           "Directory where on-demand native builds are cached."),
    EnvVar("MXNET_OPTIMIZER_AGGREGATION_SIZE", int, 60,
           "Max tensors fused into one multi-tensor optimizer update "
           "(reference: same knob)."),
    EnvVar("MXNET_PROFILER_AUTOSTART", bool, False,
           "'1' starts the profiler at import (reference: same knob)."),
    EnvVar("MXNET_TPU_COORDINATOR", str, "",
           "host:port of the jax.distributed coordination service; set "
           "by tools/launch.py for multi-process jobs."),
    EnvVar("MXNET_TPU_NUM_PROCS", int, 1,
           "World size of the multi-process job (set by the launcher)."),
    EnvVar("MXNET_TPU_PROC_ID", int, 0,
           "This process's rank (set by the launcher)."),
    EnvVar("MXNET_CHECKPOINT_ON_SIGTERM", str, "",
           "Checkpoint prefix used by mx.preemption.install() when no "
           "prefix argument is given: SIGTERM drains pending work and "
           "writes <prefix>-preempt.params/.states/.meta before exit."),
    EnvVar("MXNET_TPU_EAGER_BULK", bool, True,
           "Bulked eager dispatch: queue eager ops and replay the whole "
           "pending region as ONE jitted program at the next sync point "
           "(the reference's MXNET_EXEC_BULK_EXEC_TRAIN analog).  '0' "
           "dispatches each eager op individually."),
    EnvVar("MXNET_TPU_TEST_PLATFORM", str, "cpu",
           "Backend the test suite pins via jax.config (tests/"
           "conftest.py).  The suite's contract is 8 virtual CPU "
           "devices; set e.g. 'tpu' for a deliberate on-device run.  "
           "A dedicated var because JAX_PLATFORMS itself is forced by "
           "some environments and cannot carry user intent."),
    EnvVar("MXNET_TPU_BENCH_BUDGET_S", float, 1500.0,
           "Wall-clock budget (seconds) for bench.py: headline metrics "
           "emit first, and optional configs that would exceed the "
           "budget print a skipped line instead of running (so the "
           "bench can never outlive the driver's timeout)."),
    EnvVar("MXNET_TPU_GRAPH_CHECK", bool, False,
           "'1' runs the static graph checker (mxnet_tpu.analysis) on "
           "every Executor bind/simple_bind, raising GraphCheckError "
           "with every problem at once (unknown ops, dangling or "
           "duplicate inputs, shape contradictions) before any device "
           "time is spent.  Per-bind override: bind(..., check=True)."),
    EnvVar("MXNET_TPU_TELEMETRY", bool, False,
           "'1' enables the runtime telemetry subsystem (mx.telemetry) "
           "at import: counters/timers/events over op dispatch, "
           "compile caches, trainer steps, kvstore traffic, the input "
           "pipeline, AMP, and preemption checkpoints.  Off (the "
           "default), every hook is a single module-flag check with "
           "zero instrument calls.  Runtime toggle: "
           "mx.telemetry.enable()/disable()."),
    EnvVar("MXNET_TPU_TELEMETRY_JSONL", str, "",
           "Path of the telemetry JSONL run log.  When set, a JSONL "
           "sink is attached at import (events and timer samples "
           "stream; the aggregate snapshot lands at exit or "
           "mx.telemetry.flush()) -- analyze offline with 'python -m "
           "mxnet_tpu.telemetry summarize <path>'.  Implies nothing "
           "about MXNET_TPU_TELEMETRY: set both to record."),
    EnvVar("MXNET_TPU_CKPT_ASYNC", bool, False,
           "'1' makes CheckpointManager saves asynchronous by default: "
           "params/optimizer state snapshot to host at save() (after a "
           "waitall drain), then serialize/fsync/commit on a background "
           "thread so training overlaps the I/O.  At most one save is "
           "in flight; writer errors re-raise at the next save/wait.  "
           "Per-manager override: CheckpointManager(async_save=...)."),
    EnvVar("MXNET_TPU_CKPT_MAX_TO_KEEP", int, 0,
           "Default retention for CheckpointManager: keep at most this "
           "many step checkpoints, deleting the oldest after each save "
           "(steps matching keep_every_n_steps are exempt).  0 keeps "
           "everything.  Per-manager override: "
           "CheckpointManager(max_to_keep=...)."),
    EnvVar("MXNET_TPU_FEED_DEPTH", int, 2,
           "Default bounded-queue depth of mx.dataio.DeviceFeed: how "
           "many staged (device-resident) batches the background "
           "producer may run ahead of the consumer.  2 = classic "
           "double buffering; raise it when per-batch producer time is "
           "bursty (decode spikes).  Per-feed override: "
           "DeviceFeed(depth=...)."),
    EnvVar("MXNET_TPU_FEED_COMPACT", bool, True,
           "Ship feed batches host->device in their compact source "
           "dtype (uint8 stays uint8 -- 4x less wire traffic than its "
           "float32 cast) and expand on device via the feed's jitted "
           "transform.  '0' pre-casts host-side to the transform's "
           "target dtype before staging (A/B numerics debugging).  "
           "Per-feed override: DeviceFeed(compact=...)."),
    EnvVar("MXNET_TPU_TSAN", bool, False,
           "'1' arms the concurrency sanitizer (mxnet_tpu.sync): every "
           "Lock/RLock/Condition/Event the framework creates records "
           "per-thread acquisition stacks, maintains the lock-order "
           "graph (seeded from the static analysis pass) and raises "
           "LockOrderError on an A/B-B/A inversion, and time-bounds "
           "every untimed blocking acquisition/wait with a deadlock "
           "watchdog that dumps all thread stacks.  Off (the default), "
           "the factories return raw threading primitives -- zero "
           "overhead.  CI runs the threaded test files under this flag "
           "(ci/run_all.sh tsan)."),
    EnvVar("MXNET_TPU_TSAN_WATCHDOG_S", float, 20.0,
           "Deadlock-watchdog budget (seconds) for untimed lock "
           "acquisitions and Condition/Event waits under "
           "MXNET_TPU_TSAN=1.  On expiry the sanitizer raises "
           "DeadlockError carrying every thread's stack plus the "
           "held-locks table (who holds what, acquired where)."),
    EnvVar("MXNET_TPU_PROFILING", bool, False,
           "'1' enables compiled-step cost accounting (mx.profiling) "
           "at import: every compiled executable (eager-jit cache, "
           "hybridize cache, Executor, TrainStep) is captured for "
           "lazy XLA cost/memory analysis with a per-HLO-category "
           "breakdown, TrainStep dispatch walls feed the roofline, "
           "and host spans land on the Chrome-trace step timeline.  "
           "Off (the default), every hook is a single module-flag "
           "check.  Runtime toggle: mx.profiling.enable()/disable(); "
           "render with the mxprof CLI."),
    EnvVar("MXNET_TPU_PROFILING_DIR", str, "",
           "Directory for mx.profiling CostReport artifacts.  When "
           "set (with profiling enabled), per-executable *.cost.json "
           "files plus the combined report.json are written at "
           "interpreter exit (and by mx.profiling.save_reports()); "
           "'mxprof report'/'mxprof diff' consume them.  Unset: "
           "nothing auto-persists; save_reports(dir) still works."),
    EnvVar("MXNET_TPU_SHARD_CHECK", bool, False,
           "'1' arms the sharding sanitizer's compiled layer "
           "(mxnet_tpu.analysis.sharding): every compiled executable "
           "is registered (via the mx.profiling capture surface, which "
           "this flag also enables) so analysis.sharding."
           "collective_contract()/save_contract() can extract per-"
           "executable GSPMD collective counts/bytes, and CI's "
           "shardlint stage can diff them against the committed "
           "ci/sharding_baseline.json -- failing, with the executable "
           "and collective kind named, when a mismatched PartitionSpec "
           "turns into resharding all-gathers."),
    EnvVar("MXNET_TPU_TRANSFER_GUARD", str, "",
           "When set, applied to jax's transfer guard at import "
           "(jax.config jax_transfer_guard): one of allow | log | "
           "disallow | log_explicit | disallow_explicit.  'disallow' "
           "makes IMPLICIT host<->device transfers inside the step "
           "(a Python scalar leaking into dispatch, an un-placed index "
           "array) raise instead of silently stalling the pipeline; "
           "explicit device_put/staging keeps working.  Scoped "
           "version: analysis.sharding.transfer_guard(mode)."),
    EnvVar("MXNET_TPU_SERVING_BUCKETS", str, "1,2,4,8,16,32",
           "Default padded batch buckets (comma-separated ascending "
           "batch sizes) for mx.serving servables: a micro-batch of n "
           "requests pads to the smallest bucket >= n, and every "
           "bucket's executable is AOT-compiled and warmed at "
           "registration.  Per-servable override: "
           "ModelRegistry.register(buckets=...)."),
    EnvVar("MXNET_TPU_SERVING_MAX_WAIT_MS", float, 5.0,
           "Micro-batch assembly deadline (milliseconds) for the "
           "mx.serving dynamic batcher: a batch dispatches as soon as "
           "the largest bucket fills OR the oldest queued request has "
           "waited this long.  Lower = tighter tail latency, higher = "
           "better occupancy.  Per-servable override: "
           "ModelRegistry.register(max_wait_ms=...)."),
    EnvVar("MXNET_TPU_SERVING_QUEUE", int, 256,
           "Bounded request-queue depth per mx.serving servable.  A "
           "submit against a full queue raises ServingQueueFull "
           "(counted in serving.shed) instead of growing latency "
           "without bound -- the load-shedding/backpressure contract.  "
           "Per-servable override: ModelRegistry.register("
           "max_queue=...)."),
    EnvVar("MXNET_TPU_SERVING_KV_BLOCK", int, 16,
           "Tokens per KV-cache block in the generative decode tier "
           "(mx.serving.decode).  Smaller blocks waste less memory on "
           "partial tails (internal fragmentation is at worst one "
           "block per sequence) but widen block tables; larger blocks "
           "amortize table walks.  Per-model override: "
           "register_generative(block_size=...)."),
    EnvVar("MXNET_TPU_SERVING_KV_BLOCKS", int, 512,
           "Total preallocated KV-cache blocks per generative "
           "servable (block 0 is a reserved scratch block for padded "
           "slots).  Together with MXNET_TPU_SERVING_KV_BLOCK this is "
           "the serving memory budget: admission sheds "
           "(ServingQueueFull, kvcache.alloc_failures) when a "
           "request's whole prompt+max_new budget cannot be covered.  "
           "Per-model override: register_generative(num_blocks=...)."),
    EnvVar("MXNET_TPU_SERVING_DECODE_BUCKETS", str, "1,2,4,8",
           "Slot-count buckets for the continuous-batching decode "
           "step: each compiles one AOT executable at registration, "
           "live sequences pad to the smallest bucket that fits, and "
           "the largest bucket bounds concurrent sequences.  "
           "Per-model override: register_generative("
           "decode_buckets=...)."),
    EnvVar("MXNET_TPU_SERVING_PREFILL_BUCKETS", str, "16,32,64,128",
           "Prompt-length buckets for generative prefill: a prompt "
           "pads to the smallest bucket >= its length (largest bucket "
           "= longest admissible prompt), one warmed executable per "
           "bucket.  Per-model override: register_generative("
           "prefill_buckets=...)."),
    EnvVar("MXNET_TPU_SERVING_CACHE_DIR", str,
           "~/.cache/mxnet_tpu/serving",
           "Directory of the persistent serving compile cache: "
           "per-bucket servable programs serialized via jax.export, "
           "keyed on the normalized-StableHLO fingerprint, so a new "
           "serving process warms registration from disk.  Disable "
           "per-registry with ModelRegistry(compile_cache=False)."),
    EnvVar("MXNET_TPU_SERVING_PREDICTOR_CACHE", int, 8,
           "LRU bound on mx.Predictor's per-input-shape jit cache: at "
           "most this many compiled shape classes stay resident; the "
           "least-recently-used program is dropped beyond it (counted "
           "in serving.compile_evictions).  Per-predictor override: "
           "Predictor(jit_cache_size=...)."),
    EnvVar("MXNET_TPU_KERNELS", str, "",
           "Pallas kernel tier selection (mx.kernels, docs/kernels.md). "
           "Unset (auto): Pallas kernels only where measured profitable "
           "and only on TPU (flash attention above the seq>=256 "
           "crossover); the gluon BatchNorm+ReLU fusion sites and the "
           "bucket-flattened LARS/LAMB optimizer update stay off.  "
           "'1': the whole tier arms -- fusion sites rewrite, the "
           "bucketed optimizer replaces the per-parameter update swarm "
           "in compiled train steps, and on non-TPU backends kernels "
           "run in interpret mode so tests exercise the real kernel "
           "bodies.  '0': XLA fallback everywhere (kill switch).  Read "
           "per trace: arm before building/compiling the net."),
    EnvVar("MXNET_TPU_PERF_AUDIT_TOL", float, 0.02,
           "Absolute growth tolerance for the perf auditor's share "
           "metrics (transpose share, unfused-elementwise share, MXU "
           "pad waste) when diffing a perf audit against the blessed "
           "ci/perf_baseline.json (mxlint --perf-diff / "
           "analysis.perf.diff_audit).  A metric grown past baseline + "
           "tolerance errors naming the executable; improvements pass "
           "(docs/perf_lint.md)."),
    EnvVar("MXNET_TPU_NUMERICS_CHECK", bool, False,
           "'1' arms the non-finite sentinel "
           "(analysis.numerics.finite_sentinel): TrainStep and "
           "ContinuousTrainer fold ONE fused isfinite-reduction over "
           "the dtype-bucketed gradients into each step (one boolean, "
           "one device_get) and on the first non-finite step run an "
           "attribution pass naming WHICH parameter went NaN/Inf, "
           "raising NonFiniteError(param, step, kind) with the weights "
           "still at their pre-step values.  '0' (default): one "
           "module-flag check, zero per-step work (docs/numerics.md)."),
    EnvVar("MXNET_TPU_NUMERICS_AUDIT_TOL", float, 0.02,
           "Absolute growth tolerance for the numerics auditor's share "
           "metrics (half-accumulated dot/conv bytes, convert-storm "
           "bytes, all-half reductions) when diffing against the "
           "blessed ci/numerics_baseline.json (mxlint --numerics-diff "
           "/ analysis.numerics.diff_audit).  A metric grown past "
           "baseline + tolerance errors naming the executable; "
           "improvements pass (docs/numerics.md)."),
    EnvVar("MXNET_TPU_MEMORY_WATCH", bool, False,
           "'1' arms the live-buffer leak sentinel "
           "(analysis.memory.LeakSentinel): ContinuousTrainer censuses "
           "jax.live_arrays() at every goodput-window boundary "
           "(memory.live_bytes / memory.live_arrays gauges) and flags "
           "monotonic live-bytes growth past the EWMA+MAD baseline, "
           "naming the top-growing shape/dtype bucket -- "
           "publish-guarded, so checkpoint snapshot spikes never "
           "flag.  '0' (default): one module-flag check, zero "
           "per-step work (docs/memory.md)."),
    EnvVar("MXNET_TPU_MEMORY_AUDIT_TOL", float, 0.02,
           "Relative growth tolerance for peak_hbm_bytes when diffing "
           "a memory audit against the blessed ci/memory_baseline.json "
           "(mxlint --memory-diff / analysis.memory.diff_audit).  An "
           "executable whose peak HBM grew past baseline x (1 + tol) "
           "errors naming it; shrinkage passes (docs/memory.md)."),
    EnvVar("MXNET_TPU_CKPT_QUARANTINE", bool, True,
           "Checkpoint discovery quarantine: a step that fails "
           "manifest/CRC verification during "
           "CheckpointManager.latest_step() is renamed "
           "step_<N>.corrupt (counted in checkpoint.quarantined) "
           "instead of silently skipped, so rollbacks are visible to "
           "operators and the torn bytes survive as evidence.  '0' "
           "restores skip-only discovery.  Per-manager override: "
           "CheckpointManager(quarantine=...)."),
    EnvVar("MXNET_TPU_CKPT_WRITE_RETRIES", int, 2,
           "How many times the async checkpoint writer retries a "
           "failed background write (exponential backoff from "
           "MXNET_TPU_CKPT_RETRY_BACKOFF_S) before surfacing the "
           "error through the checkpoint.write_failed telemetry event "
           "and the next save()/wait_until_finished().  0 disables "
           "retries.  Per-writer override: AsyncWriter(retries=...)."),
    EnvVar("MXNET_TPU_CKPT_RETRY_BACKOFF_S", float, 0.25,
           "Initial backoff (seconds) between async checkpoint write "
           "retries; doubles per attempt."),
    EnvVar("MXNET_TPU_CHAOS_SEED", int, 0,
           "Default seed for mx.chaos.arm(): per-rule probability "
           "streams derive from (seed, fail point, rule index), so a "
           "chaos scenario replays identically for a fixed seed.  "
           "Chaos is only ever armed programmatically "
           "(chaos.arm()/chaos.scenario()); no env var can arm fail "
           "points in a production process."),
    EnvVar("MXNET_TPU_SERVING_POLL_S", float, 0.5,
           "RegistryWatcher poll interval (seconds): how often the "
           "checkpoint root is scanned for a newer verified step to "
           "hot-swap into the servable.  Per-watcher override: "
           "RegistryWatcher(poll_s=...)."),
    EnvVar("MXNET_TPU_SERVING_SWAP_RETRIES", int, 2,
           "How many times a RegistryWatcher retries an aborted "
           "hot-swap (exponential backoff from "
           "MXNET_TPU_SERVING_SWAP_BACKOFF_S) before marking the step "
           "bad and keeping the previous model in service.  "
           "Per-watcher override: RegistryWatcher(swap_retries=...)."),
    EnvVar("MXNET_TPU_SERVING_SWAP_BACKOFF_S", float, 0.25,
           "Initial backoff (seconds) between hot-swap retries; "
           "doubles per attempt."),
    EnvVar("MXNET_TPU_SERVING_SWAP_BUDGET", int, 3,
           "RegistryWatcher failure budget: after this many "
           "CONSECUTIVE steps fail to swap (each already retried), "
           "the watcher suspends itself with a warning instead of "
           "flapping -- the last good model keeps serving until an "
           "operator intervenes.  Per-watcher override: "
           "RegistryWatcher(failure_budget=...)."),
    EnvVar("MXNET_TPU_OBS_TRACE", bool, False,
           "'1' arms request/step tracing (mx.obs): context-propagated "
           "trace/span IDs through the serving path (submit -> queue "
           "wait -> batch assembly -> compiled dispatch -> device_get "
           "-> respond, batcher fan-in as span links) and the training "
           "loop (step -> publish -> checkpoint commit -> watcher "
           "discover -> warm -> install), streamed into the telemetry "
           "JSONL as span records and exportable as Chrome-trace JSON "
           "(obs.export_chrome_trace).  Off (the default), every "
           "traced site is a single module-flag check with zero trace "
           "calls.  Runtime toggle: obs.enable_tracing()/"
           "disable_tracing()."),
    EnvVar("MXNET_TPU_OBS_BLACKBOX", str, "",
           "Path of the crash-safe flight recorder (mx.obs.flight).  "
           "When set, an mmap'd ring of the most recent telemetry "
           "records/spans is installed at import and survives "
           "os._exit/SIGKILL; it is marked+msync'd automatically from "
           "the preemption handler, the chaos KILL path, and SIGUSR2 "
           "(which also snapshots every thread's stack).  Render with "
           "'mxtelemetry blackbox <path>'."),
    EnvVar("MXNET_TPU_OBS_BLACKBOX_KB", int, 256,
           "Flight-recorder ring capacity in KiB (the final-seconds "
           "window an operator gets after a crash).  Per-recorder "
           "override: obs.install_blackbox(capacity=...)."),
    EnvVar("MXNET_TPU_OBS_PORT", int, 0,
           "Port of the live-introspection HTTP server (mx.obs."
           "server, localhost): /healthz (watcher failure budget + "
           "async-writer failures + queue saturation -> READY/"
           "NOT_READY), /metrics (Prometheus exposition of the live "
           "registry), /statusz (served/published step, swap history, "
           "bucket occupancy, per-rank heartbeats).  0 (default) = "
           "not started; obs.serve(0) binds an ephemeral port."),
    EnvVar("MXNET_TPU_OBS_GOODPUT", bool, False,
           "'1' arms the goodput ledger (mx.obs.goodput): the "
           "ContinuousTrainer loop ticks a per-process StepLedger that "
           "decomposes every rolling window of training steps into "
           "device_compute / input_wait / host_sync / checkpoint_stall "
           "/ recompile / other (reconciled to window wall within "
           "MXNET_TPU_OBS_GOODPUT_TOL), publishes a rolling MFU gauge, "
           "and runs the EWMA+MAD regression sentinel (goodput.* "
           "instruments, /statusz goodput section).  Needs "
           "MXNET_TPU_TELEMETRY=1 for non-empty attribution.  Off "
           "(default): one module-flag check per loop step.  Runtime "
           "toggle: obs.enable_goodput()/disable_goodput()."),
    EnvVar("MXNET_TPU_OBS_GOODPUT_WINDOW", int, 20,
           "Training steps per goodput-ledger window: the attribution "
           "granularity AND the sentinel's sample size.  Smaller = "
           "faster regression detection, noisier baselines.  "
           "Per-ledger override: StepLedger(window_steps=...)."),
    EnvVar("MXNET_TPU_OBS_GOODPUT_TOL", float, 0.25,
           "Reconciliation tolerance of the goodput ledger: the "
           "attributed categories may exceed the window wall by at "
           "most this fraction before the window's reconciliation "
           "contract reads failed ('other' absorbs undershoot, so "
           "only overshoot -- double counting -- can violate it).  "
           "CI gates ok on every window (ci/run_all.sh obs)."),
    EnvVar("MXNET_TPU_OBS_GOODPUT_MAD_K", float, 4.0,
           "Regression-sentinel sensitivity: a category regresses when "
           "its per-step seconds exceed EWMA mean + this many EWMA "
           "absolute deviations (and the move is at least 5% of the "
           "window wall).  Per-ledger override: "
           "StepLedger(mad_k=...)."),
    EnvVar("MXNET_TPU_CHAOS_SPEC", str, "",
           "Serialized chaos scenario (chaos.make_spec() JSON: seed + "
           "rules with per-rank/per-generation scoping) for launched "
           "multi-process test harnesses.  NEVER arms anything by "
           "itself: a worker replays it only by explicitly calling "
           "chaos.arm_from_spec(), so production processes stay inert "
           "with the variable present (the env-inert contract of "
           "chaos.arm())."),
    EnvVar("MXNET_TPU_GENERATION", int, 0,
           "Supervisor generation id of this worker world, bumped by "
           "the elastic restart supervisor (tools/launch.py "
           "--supervise) on every relaunch.  Namespaces every "
           "coordination-KV key (barriers, collectives, liveness "
           "leases), and the new generation's first rendezvous sweeps "
           "the previous generation's keys."),
    EnvVar("MXNET_TPU_DIST_BARRIER_TIMEOUT_MS", int, 60000,
           "Default bound on every attributed barrier rendezvous "
           "(distributed.barrier and the sharded-checkpoint commit "
           "gates).  On expiry survivors raise a typed BarrierTimeout "
           "naming the missing rank(s) -- never a raw jaxlib "
           "DEADLINE_EXCEEDED.  Per-call override: "
           "barrier(timeout_ms=...)."),
    EnvVar("MXNET_TPU_DIST_LEASE_TTL_S", float, 10.0,
           "Liveness-lease staleness bound: a rank whose "
           "mxlive/g<gen>/<rank> coordination key is older than this "
           "(or absent) is reported 'presumed dead' in "
           "BarrierTimeout/RankFailure attribution.  The training "
           "loop beats the lease every step; every barrier entry "
           "refreshes it too."),
    EnvVar("MXNET_TPU_DIST_KV_RETRIES", int, 2,
           "Bounded retries (doubling backoff from 50 ms) for "
           "TRANSIENT coordination-KV errors in host collectives and "
           "barriers.  Deadline expiries are not transient -- they "
           "attribute a missing peer and raise typed errors "
           "immediately.  0 disables retries."),
    EnvVar("MXNET_TPU_SUPERVISOR_RESTARTS", int, 3,
           "Elastic-restart budget: how many times the supervisor "
           "(tools/launch.py --supervise / mxnet_tpu.supervisor) "
           "relaunches the world after a rank death before going "
           "terminal (supervisor.exhausted event, /healthz NOT_READY)."
           "  Per-supervisor override: Supervisor(max_restarts=...)."),
    EnvVar("MXNET_TPU_SUPERVISOR_GRACE_S", float, 15.0,
           "After the first rank exit of a generation, how long the "
           "supervisor waits for the survivors to notice (typed "
           "BarrierTimeout) and exit on their own before killing the "
           "process tree.  Set it above "
           "MXNET_TPU_DIST_BARRIER_TIMEOUT_MS so survivor logs carry "
           "the attributed error."),
    EnvVar("MXNET_TPU_EAGER_BULK_MAX", int, 512,
           "Capacity flush threshold for the bulked eager queue: a "
           "pending region is flushed once it reaches this many ops, "
           "bounding host memory for loops that never sync (reference: "
           "MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN)."),
    EnvVar("MXNET_TPU_OBS_ENDPOINTS_DIR", str, "",
           "Fleet endpoint-discovery directory (obs.fleet): every obs "
           "server atomically publishes its {pid, rank, generation, "
           "port} there on serve() and a FleetMonitor discovers the "
           "replica set from it.  The supervisor threads this into "
           "every launched world so relaunched generations "
           "re-register automatically.  Empty (default) disables "
           "publication."),
    EnvVar("MXNET_TPU_OBS_SCRAPE_MS", float, 1000.0,
           "FleetMonitor scrape interval in milliseconds.  The "
           "presumed-down TTL defaults to 3x this, so a replica that "
           "stops answering is declared down within ~3 scrape "
           "rounds."),
    EnvVar("MXNET_TPU_OBS_ALERT_RULES", str, "",
           "JSON list of SLO alert-rule overrides merged onto the "
           "stock rules by name (obs.alerts.parse_rules): e.g. "
           "'[{\"name\": \"p99_latency_ms\", \"threshold\": 250}]'.  "
           "Unparseable specs raise loudly -- a silently-ignored "
           "alert config is the worst failure mode an alerting plane "
           "can have."),
]

REGISTRY = {v.name: v for v in _VARS}


def get(name):
    """Typed read of a registered env var (raises for unknown names, so
    typos fail loudly instead of silently defaulting)."""
    if name not in REGISTRY:
        raise MXNetError("unknown env var %r; registered: %s"
                         % (name, ", ".join(sorted(REGISTRY))))
    return REGISTRY[name].read()


def describe():
    """{name: (current_value, default, doc)} for every registered var."""
    return {v.name: (v.read(), v.default, v.doc) for v in _VARS}


def generate_doc(path=None):
    """Render the env-var reference page (reference: env_var.md)."""
    lines = ["# Environment variables",
             "",
             "Generated from `mxnet_tpu/env.py` -- the registry the "
             "framework actually reads, so this page cannot go stale.",
             "",
             "| Variable | Type | Default | Description |",
             "|---|---|---|---|"]
    for v in _VARS:
        lines.append("| `%s` | %s | `%r` | %s |"
                     % (v.name, v.type.__name__, v.default, v.doc))
    text = "\n".join(lines) + "\n"
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
