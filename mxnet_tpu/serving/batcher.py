"""Dynamic micro-batcher: the request queue between concurrent clients
and the per-bucket executor pool.

Clipper-shaped adaptive batching: a ``sync``-disciplined bounded queue
accepts single-sample requests; one worker thread per servable
assembles micro-batches -- it dispatches as soon as the largest bucket
fills OR the oldest queued request has waited ``max_wait``; the batch
pads to the smallest bucket that fits, runs ONE compiled call, and the
responses scatter back to per-request futures.

Overload behavior is explicit, not emergent:

- **backpressure / load-shedding**: a full queue rejects the submit
  with :class:`ServingQueueFull` (``serving.shed`` counts them) instead
  of growing latency without bound;
- **per-request timeout**: a request whose deadline passes while still
  queued is completed with :class:`RequestTimeout` and never occupies
  a batch slot (once dispatched, a request always completes);
- **graceful drain**: ``close(drain=True)`` stops intake, the worker
  keeps dispatching until the queue is empty, and every accepted
  request resolves -- zero dropped responses on shutdown.
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

import numpy as np

from .. import chaos as _chaos
from .. import obs as _obs
from .. import sync as _sync
from .. import telemetry as _telemetry
from ..base import MXNetError

__all__ = ["DynamicBatcher", "ServingQueueFull", "RequestTimeout",
           "ServableClosed"]


class ServingQueueFull(MXNetError):
    """Submit rejected: the bounded request queue is at capacity (the
    load-shedding contract -- back off or scale out)."""


class RequestTimeout(MXNetError):
    """The request's deadline passed while it was still queued."""


class ServableClosed(MXNetError):
    """Submit rejected: the servable is closed or draining."""


class _Request:
    __slots__ = ("x", "future", "t_submit", "deadline", "tctx")

    def __init__(self, x, timeout):
        self.x = x
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = (self.t_submit + timeout) if timeout else None
        # trace position of this request (obs tracing only): the
        # submitter's context if it carries one, else a fresh trace --
        # the worker thread records queue/respond spans against it
        self.tctx = None


# Worker idle poll: the condition is notified on submit/close, so this
# bound only matters for watchdog friendliness under MXNET_TPU_TSAN=1
# (an idle servable must not trip the untimed-wait deadlock watchdog).
_IDLE_WAIT_S = 0.1


class DynamicBatcher:
    """One request queue + worker thread over a BucketExecutorPool."""

    def __init__(self, pool, label="servable", max_wait_ms=None,
                 max_queue=None):
        from .. import env as _env
        self._pool = pool
        self._label = label
        if max_wait_ms is None:
            max_wait_ms = _env.get("MXNET_TPU_SERVING_MAX_WAIT_MS")
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue = int(max_queue
                             if max_queue is not None
                             else _env.get("MXNET_TPU_SERVING_QUEUE"))
        self._cond = _sync.Condition(name="serving.queue")
        self._queue = collections.deque()
        self._closed = False
        self._drain = True
        self._thread = threading.Thread(
            target=self._worker, daemon=True,
            name="mxtpu-serving-%s" % label)
        self._thread.start()

    # -- intake ---------------------------------------------------------
    def submit(self, x, timeout=None):
        """Queue one sample; returns a Future resolving to the model's
        output for that sample (a tuple when the model has several
        outputs).  Raises ServingQueueFull / ServableClosed instead of
        blocking -- backpressure is the caller's signal to shed."""
        x = np.asarray(x, self._pool.dtype)
        if x.shape != self._pool.input_shape:
            raise MXNetError(
                "serving: request shape %r != input shape %r (requests "
                "carry ONE sample; the batcher builds the batch)"
                % (x.shape, self._pool.input_shape))
        req = _Request(x, timeout)
        if _obs._TRACE_ENABLED:
            req.tctx = _obs.trace.fresh_context()
        shed = closed = False
        with self._cond:
            if self._closed:
                closed = True
            elif len(self._queue) >= self.max_queue:
                shed = True
            else:
                self._queue.append(req)
                depth = len(self._queue)
                self._cond.notify()
        if closed:
            raise ServableClosed("servable %r is closed" % self._label)
        if shed:
            if _telemetry._ENABLED:
                _telemetry.hooks.serving_shed(self._label)
            raise ServingQueueFull(
                "servable %r queue full (%d); request shed"
                % (self._label, self.max_queue))
        if _telemetry._ENABLED:
            _telemetry.hooks.serving_request(self._label, depth)
        return req.future

    # -- worker ---------------------------------------------------------
    def _collect(self):
        """Assemble one micro-batch: wait for a first request, then
        gather more until the largest bucket fills or the oldest
        request's ``max_wait`` assembly deadline passes.  Returns the
        popped requests, or None when closed and drained."""
        max_n = self._pool.max_bucket
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                self._cond.wait(_IDLE_WAIT_S)
            deadline = self._queue[0].t_submit + self.max_wait_s
            while len(self._queue) < max_n and not self._closed:
                rem = deadline - time.perf_counter()
                if rem <= 0:
                    break
                self._cond.wait(rem)
            n = min(len(self._queue), max_n)
            reqs = [self._queue.popleft() for _ in range(n)]
        return reqs

    def _worker(self):
        while True:
            reqs = self._collect()
            if reqs is None:
                return
            if not self._drain and self._closed:
                for r in reqs:
                    r.future.set_exception(ServableClosed(
                        "servable %r closed without drain" % self._label))
                continue
            now = time.perf_counter()
            live = []
            for r in reqs:
                if r.deadline is not None and now > r.deadline:
                    if _telemetry._ENABLED:
                        _telemetry.hooks.serving_timeout(self._label)
                    if _obs._TRACE_ENABLED and r.tctx is not None:
                        _obs.record_span(
                            "serving.request", r.tctx,
                            t0=r.t_submit, dur=now - r.t_submit,
                            attrs={"model": self._label,
                                   "timeout": True})
                    r.future.set_exception(RequestTimeout(
                        "request waited %.1fms > timeout"
                        % (1e3 * (now - r.t_submit))))
                else:
                    live.append(r)
            if live:
                self._dispatch(live)

    def _dispatch(self, reqs):
        import jax
        n = len(reqs)
        bucket = self._pool.bucket_for(n)
        batch = np.zeros((bucket,) + self._pool.input_shape,
                         self._pool.dtype)
        for i, r in enumerate(reqs):
            batch[i] = r.x
        t0 = time.perf_counter()
        try:
            # chaos: a sleep rule here is the wedged-device weather the
            # flood scenario sheds against; a RAISE rule proves a
            # failed dispatch fails its requests, not the worker
            _chaos.fail_point("serving.dispatch", model=self._label,
                              occupancy=n, bucket=bucket)
            outs = self._pool.call(bucket, batch)
            t_call = time.perf_counter()
            outs = jax.device_get(outs)       # one gather for the batch
        except Exception as e:                # compiled call failed:
            if _telemetry._ENABLED:           # the fleet error_ratio
                _telemetry.hooks.serving_error(self._label)
            for r in reqs:                    # fail the REQUESTS, keep
                r.future.set_exception(e)     # the worker alive
            return
        t_get = time.perf_counter()
        dt = t_get - t0
        single = len(outs) == 1
        for i, r in enumerate(reqs):
            r.future.set_result(outs[0][i] if single
                                else tuple(o[i] for o in outs))
        done = time.perf_counter()
        if _telemetry._ENABLED:
            _telemetry.hooks.serving_batch(self._label, n, bucket, dt)
            for r in reqs:
                _telemetry.hooks.serving_latency(done - r.t_submit)
        if _obs._TRACE_ENABLED:
            self._record_batch_spans(reqs, t0, t_call, t_get, done,
                                     n, bucket)

    def _record_batch_spans(self, reqs, t0, t_call, t_get, done, n,
                            bucket):
        """The serving causality record (obs tracing armed): each
        request's trace gets queue-wait and respond child spans plus a
        ``serving.request`` root; the batch itself is a fresh trace
        whose root span LINKS every request span it served (Dapper
        fan-in) with ``serving.batch_assembly`` / ``serving.dispatch``
        / ``serving.device_get`` children.  ``serving.dispatch`` +
        ``serving.device_get`` durations sum to exactly the window the
        ``serving.dispatch_time`` timer observed -- the
        span-vs-telemetry reconciliation CI's obs stage gates."""
        tr = _obs.trace
        model = self._label
        links = []
        for r in reqs:
            ctx = r.tctx
            if ctx is None:           # accepted before tracing armed
                continue
            links.append(ctx.span_id)
            tr.record_span("serving.queue_wait", ctx.child(),
                           parent_id=ctx.span_id, t0=r.t_submit,
                           dur=t0 - r.t_submit,
                           attrs={"model": model})
            tr.record_span("serving.respond", ctx.child(),
                           parent_id=ctx.span_id, t0=t_get,
                           dur=done - t_get, attrs={"model": model})
            tr.record_span("serving.request", ctx, t0=r.t_submit,
                           dur=done - r.t_submit,
                           attrs={"model": model, "bucket": bucket})
        batch_ctx = tr.TraceContext(tr.new_id(), tr.new_id())
        t_first = min(r.t_submit for r in reqs)
        tr.record_span("serving.batch_assembly", batch_ctx.child(),
                       parent_id=batch_ctx.span_id, t0=t_first,
                       dur=t0 - t_first, attrs={"model": model})
        tr.record_span("serving.dispatch", batch_ctx.child(),
                       parent_id=batch_ctx.span_id, t0=t0,
                       dur=t_call - t0,
                       attrs={"model": model, "bucket": bucket})
        tr.record_span("serving.device_get", batch_ctx.child(),
                       parent_id=batch_ctx.span_id, t0=t_call,
                       dur=t_get - t_call, attrs={"model": model})
        tr.record_span("serving.batch", batch_ctx, t0=t0,
                       dur=done - t0,
                       attrs={"model": model, "occupancy": n,
                              "bucket": bucket}, links=links)

    # -- lifecycle ------------------------------------------------------
    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    def close(self, drain=True):
        """Stop intake and shut the worker down.  ``drain=True``
        (default) dispatches everything already queued first, so every
        accepted request resolves; ``drain=False`` fails the queued
        requests with ServableClosed (still resolved, never dropped)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._drain = drain
            self._cond.notify_all()
        self._thread.join()

    @property
    def closed(self):
        return self._closed
