"""Multi-tenant model registry: sources -> servable handles.

A :class:`Servable` is one deployed model: a pure forward function +
device-resident weights, an AOT-compiled per-bucket executor pool
(warmed at registration), and a dynamic batcher with its own worker
thread and bounded queue.  The :class:`ModelRegistry` owns many of them
by name -- the multi-tenant surface a serving process exposes.

Model sources (all land in the same ``fn(params, x) -> outs`` shape):

- **Gluon block** (``block=``): ``HybridBlock.functionalize`` --
  the same pure-function extraction the compiled trainer and the
  ``.mxa`` edge export use.
- **symbol+params** (``symbol=`` / ``params=``): a ``-symbol.json``
  graph (path or Symbol) evaluated through the symbol executor, with
  the reference's ``arg:``/``aux:`` key prefixes accepted.
- **ONNX** (``onnx=``): ``mx.onnx.import_model`` -- including
  third-party protobufs, not just our own exports.
- **checkpoint** (``checkpoint=`` + ``block=``): params restored from a
  PR-3 manifest-verified :class:`~mxnet_tpu.checkpoint.CheckpointManager`
  step (the newest intact step by default) into the block, then served
  as a block source.
"""
from __future__ import annotations

import numpy as np

from .. import chaos as _chaos
from .. import obs as _obs
from .. import telemetry as _telemetry
from ..base import MXNetError
from .batcher import DynamicBatcher, ServableClosed
from .cache import CompileCache
from .executor import BucketExecutorPool

__all__ = ["ModelRegistry", "Servable"]


def _default_buckets():
    from .. import env as _env
    spec = _env.get("MXNET_TPU_SERVING_BUCKETS")
    try:
        buckets = tuple(int(tok) for tok in str(spec).split(",") if tok)
    except ValueError as e:
        raise MXNetError("MXNET_TPU_SERVING_BUCKETS=%r is not a "
                         "comma-separated int list" % (spec,)) from e
    return buckets


def _strip_prefixes(params):
    return {(k.split(":", 1)[1] if ":" in k else k): v
            for k, v in params.items()}


def _device_value(v):
    """Any array-ish (NDArray / numpy / jax) -> jax array."""
    import jax.numpy as jnp
    data = getattr(v, "_data", v)
    return jnp.asarray(np.asarray(data) if not hasattr(data, "dtype")
                       else data)


class Servable:
    """One deployed model: executor pool + dynamic batcher."""

    def __init__(self, name, pool, batcher, source):
        self.name = name
        self.source = source
        self._pool = pool
        self._batcher = batcher

    # -- client surface -------------------------------------------------
    def submit(self, x, timeout=None):
        """Queue one sample; returns a ``concurrent.futures.Future``."""
        return self._batcher.submit(x, timeout=timeout)

    def infer(self, x, timeout=None):
        """Blocking single-sample inference: submit + wait.  The
        ``timeout`` bounds the whole round trip (queue wait included)."""
        fut = self.submit(x, timeout=timeout)
        return fut.result(timeout=timeout)

    # -- introspection --------------------------------------------------
    @property
    def buckets(self):
        return self._pool.buckets

    @property
    def input_shape(self):
        return self._pool.input_shape

    @property
    def dtype(self):
        return self._pool.dtype

    def fingerprint(self, bucket):
        return self._pool.fingerprint(bucket)

    def queue_depth(self):
        return self._batcher.queue_depth()

    @property
    def queue_capacity(self):
        """Bounded queue depth past which submits shed (the
        `/healthz` queue-saturation signal reads depth vs this)."""
        return self._batcher.max_queue

    @property
    def closed(self):
        return self._batcher.closed

    def close(self, drain=True):
        self._batcher.close(drain=drain)

    def __repr__(self):
        return "Servable(%r, source=%r, buckets=%r, input=%r)" % (
            self.name, self.source, self.buckets, self.input_shape)


class ModelRegistry:
    """Name -> Servable store; the multi-tenant serving surface.

    ::

        reg = mx.serving.ModelRegistry()
        reg.register("lenet", block=net, input_shape=(1, 28, 28))
        y = reg.infer("lenet", x)          # dynamically batched
        reg.shutdown(drain=True)
    """

    def __init__(self, cache_dir=None, compile_cache=True):
        from .. import sync as _sync
        self._lock = _sync.Lock(name="serving.registry")
        self._servables = {}
        self._cache = CompileCache(cache_dir) if compile_cache else None
        _obs.status.register_registry(self)   # weak: /healthz, /statusz

    # -- registration ---------------------------------------------------
    def register(self, name, block=None, symbol=None, params=None,
                 onnx=None, checkpoint=None, step=None, input_shape=None,
                 dtype="float32", input_name=None, buckets=None,
                 max_wait_ms=None, max_queue=None, warmup=True):
        """Load a model from one source into a warm servable handle.

        Exactly one of ``block``, ``symbol``, ``onnx`` must be given
        (``checkpoint`` composes with ``block``).  ``input_shape`` is
        the per-sample shape (no batch dim) and is required for every
        source.  Registration compiles and warms every bucket, so no
        request pays a first-compile; re-registering a name drains and
        replaces the previous servable.
        """
        if input_shape is None:
            raise MXNetError("serving.register needs input_shape "
                             "(per-sample, no batch dim)")
        sources = [s is not None for s in (block, symbol, onnx)]
        if checkpoint is not None and block is None:
            raise MXNetError("checkpoint= needs block= for the "
                             "architecture (a manifest stores params)")
        if sum(sources) != 1:
            raise MXNetError("serving.register needs exactly one of "
                             "block= / symbol= / onnx=")
        if checkpoint is not None:
            self._restore_checkpoint(block, checkpoint, step)
            source = "checkpoint"
        elif block is not None:
            source = "block"
        elif onnx is not None:
            source = "onnx"
        else:
            source = "symbol"
        if block is not None:
            fn, pvals = self._from_block(block, input_shape, dtype)
        else:
            if onnx is not None:
                from ..onnx import import_model
                sym, arg_params, aux_params = import_model(onnx)
                pdict = {}
                pdict.update(arg_params)
                pdict.update(aux_params)
            else:
                sym, pdict = self._load_symbol(symbol, params)
            fn, pvals = self._from_symbol(sym, pdict, input_name)

        buckets = tuple(buckets) if buckets else _default_buckets()
        pool = BucketExecutorPool(fn, pvals, input_shape, dtype, buckets,
                                  cache=self._cache, label=name)
        if warmup:
            _w = _obs.begin_span("serving.register.warm", model=name) \
                if _obs._TRACE_ENABLED else None
            try:
                pool.warmup()
            finally:
                if _w is not None:
                    _obs.end_span(_w)
            self._validate_hbm(name, pool)
        # chaos: an abort here (after the expensive warm-up, before the
        # install) models every way a swap dies late; the previous
        # servable MUST keep serving untouched -- the watcher's
        # retry/backoff and failure budget hang off this contract
        _chaos.fail_point("serving.swap", model=name)
        _i = _obs.begin_span("serving.register.install", model=name) \
            if _obs._TRACE_ENABLED else None
        try:
            batcher = DynamicBatcher(pool, label=name,
                                     max_wait_ms=max_wait_ms,
                                     max_queue=max_queue)
            servable = Servable(name, pool, batcher, source)
            with self._lock:
                old = self._servables.get(name)
                self._servables[name] = servable
            if old is not None:
                old.close(drain=True)
        finally:
            if _i is not None:
                _obs.end_span(_i)
        if _telemetry._ENABLED:
            _telemetry.hooks.serving_model(name, source, len(buckets))
        return servable

    def _validate_hbm(self, name, pool):
        """HBM bucket validation (ISSUE 20): when the backend reports a
        device memory limit, predict every bucket's peak HBM along the
        hbm_plan line and warn on buckets that cannot fit --
        registration still succeeds (an oversized bucket may never be
        dispatched), but the operator hears it BEFORE an OOM does the
        telling.  No-op on backends without memory_stats (CPU)."""
        from ..analysis import memory as _memory
        limit = _memory.device_hbm_bytes()
        if not limit:
            return None
        try:
            plan = pool.hbm_plan(limit)
        except Exception:
            return None             # planning must never block a swap
        bad = [str(b["batch"]) for b in plan["buckets"]
               if b["fits"] is False]
        if bad:
            import warnings
            warnings.warn(
                "servable %r: predicted peak HBM exceeds the device "
                "limit for bucket(s) %s (largest fitting bucket: %s); "
                "see analysis.memory.hbm_plan / docs/memory.md"
                % (name, ", ".join(bad), plan["largest_fit_bucket"]),
                RuntimeWarning, stacklevel=3)
        return plan

    def register_generative(self, name, model, params=None,
                            checkpoint=None, step=None,
                            prefill_buckets=None, decode_buckets=None,
                            block_size=None, num_blocks=None,
                            max_queue=None, warmup=True,
                            kv_dtype="float32"):
        """Deploy an autoregressive decoder as a generative servable.

        ``model`` is the pure-function spec
        (:class:`~mxnet_tpu.serving.decode.TinyGPT`-shaped); weights
        come from ``params=`` (a flat name->array dict) or
        ``checkpoint=`` (a :class:`~mxnet_tpu.checkpoint.\
CheckpointManager` root whose step carries a ``params`` item).
        Registration warms every prefill and decode bucket, then
        installs; re-registering a name swaps mid-decode safely -- the
        old engine drains its half-generated sequences to completion on
        its own executables while the replacement takes new requests
        (zero dropped, ``chaos.survived.serving.decode_swap``).
        """
        from .decode.engine import DecodeEngine, GenerativeServable
        if (params is None) == (checkpoint is None):
            raise MXNetError("register_generative needs exactly one "
                             "of params= / checkpoint=")
        if checkpoint is not None:
            params = self._restore_params(checkpoint, step)
        pvals = {k: _device_value(v) for k, v in params.items()}
        engine = DecodeEngine(model, pvals,
                              prefill_buckets=prefill_buckets,
                              decode_buckets=decode_buckets,
                              block_size=block_size,
                              num_blocks=num_blocks,
                              max_queue=max_queue, cache=self._cache,
                              label=name, kv_dtype=kv_dtype)
        if warmup:
            _w = _obs.begin_span("serving.register.warm", model=name) \
                if _obs._TRACE_ENABLED else None
            try:
                engine.warmup()
            finally:
                if _w is not None:
                    _obs.end_span(_w)
        # same late-abort contract as register(): a chaos fault here
        # (warmed, not yet installed) must leave the old servable --
        # and every sequence it is mid-way through generating --
        # untouched
        _chaos.fail_point("serving.swap", model=name)
        _i = _obs.begin_span("serving.register.install", model=name) \
            if _obs._TRACE_ENABLED else None
        try:
            engine.start()
            servable = GenerativeServable(name, engine)
            with self._lock:
                old = self._servables.get(name)
                self._servables[name] = servable
            if old is not None:
                # drain=True keeps STEPPING the old engine until every
                # half-generated sequence finishes on the old weights
                live = old.close(drain=True)
                if live:
                    _chaos.survived("serving.decode_swap",
                                    "drained %d live" % live)
        finally:
            if _i is not None:
                _obs.end_span(_i)
        if _telemetry._ENABLED:
            _telemetry.hooks.serving_model(
                name, "generative",
                len(engine.prefill_buckets)
                + len(engine.decode_buckets))
        return servable

    @staticmethod
    def _restore_params(checkpoint, step):
        from ..checkpoint import CheckpointManager
        mgr = checkpoint if isinstance(checkpoint, CheckpointManager) \
            else CheckpointManager(checkpoint)
        ckpt = mgr.restore(step=step)
        if ckpt is None:
            raise MXNetError("serving: no intact checkpoint under %r"
                             % mgr.root)
        if "params" not in ckpt.items:
            raise MXNetError(
                "serving: checkpoint step %d has no 'params' item "
                "(items: %s)" % (ckpt.step, sorted(ckpt.items)))
        return ckpt.items["params"]

    @staticmethod
    def _restore_checkpoint(block, checkpoint, step):
        from ..checkpoint import CheckpointManager
        mgr = checkpoint if isinstance(checkpoint, CheckpointManager) \
            else CheckpointManager(checkpoint)
        ckpt = mgr.restore_training(block, step=step)
        if ckpt is None:
            raise MXNetError("serving: no intact checkpoint under %r"
                             % mgr.root)
        return ckpt

    @staticmethod
    def _from_block(block, input_shape, dtype):
        import jax
        if not hasattr(block, "functionalize"):
            raise MXNetError("serving: block= expects a HybridBlock")
        if any(p._data is None for p in block._all_params()):
            # materialize deferred params with one probe forward (the
            # export_compiled idiom)
            from .. import ndarray as nd
            probe = nd.zeros((1,) + tuple(input_shape)).astype(dtype)
            block(probe)
        pure_fn, pnames, pmap = block.functionalize(training=False)
        pvals = {n: pmap[n].data()._data for n in pnames}
        key = jax.random.PRNGKey(0)

        def fn(params, x):
            outs, _aux = pure_fn(params, [x], key)
            return tuple(outs)

        return fn, pvals

    @staticmethod
    def _load_symbol(symbol, params):
        from .. import ndarray as nd
        from ..symbol import symbol as sym_mod
        sym = sym_mod.load(symbol) if isinstance(symbol, str) else symbol
        if isinstance(params, str):
            params = nd.load(params)
        return sym, _strip_prefixes(dict(params or {}))

    @staticmethod
    def _from_symbol(sym, params, input_name):
        from ..symbol.symbol import _eval_symbol
        arg_names = sym.list_arguments()
        aux_names = set(sym.list_auxiliary_states())
        inputs = [n for n in arg_names
                  if n not in params and n not in aux_names]
        if input_name is None:
            if len(inputs) != 1:
                raise MXNetError(
                    "serving: graph has inputs %r; pass input_name= to "
                    "pick the batched one (others must be in params)"
                    % (inputs,))
            input_name = inputs[0]
        elif input_name not in arg_names:
            raise MXNetError("serving: unknown input %r (arguments: %s)"
                             % (input_name, arg_names))
        missing = [n for n in aux_names if n not in params]
        if missing:
            raise MXNetError("serving: aux states %r missing from "
                             "params" % (missing,))
        pvals = {n: _device_value(v) for n, v in params.items()}

        def fn(pv, x):
            feed = dict(pv)
            feed[input_name] = x
            outs = _eval_symbol(sym, feed)
            return tuple(o._data for o in outs)

        return fn, pvals

    # -- lookup / client ------------------------------------------------
    def servable(self, name):
        with self._lock:
            s = self._servables.get(name)
        if s is None:
            raise MXNetError("serving: no servable %r (registered: %s)"
                             % (name, self.names()))
        return s

    def names(self):
        with self._lock:
            return sorted(self._servables)

    def submit(self, name, x, timeout=None):
        """Queue one sample on the named servable.  A concurrent
        re-register (hot swap) can close the handle between the lookup
        and the submit; the replacement is already installed by then,
        so the lookup retries against it -- a swap is invisible to
        registry-path clients (zero dropped requests, proven under
        chaos in tests/test_chaos.py)."""
        for _ in range(8):
            s = self.servable(name)
            try:
                return s.submit(x, timeout=timeout)
            except ServableClosed:
                with self._lock:
                    cur = self._servables.get(name)
                if cur is None or cur is s:
                    raise               # really closed, not swapped
        raise ServableClosed(
            "serving: servable %r kept closing mid-submit (flapping "
            "re-registration?)" % name)

    def infer(self, name, x, timeout=None):
        fut = self.submit(name, x, timeout=timeout)
        return fut.result(timeout=timeout)

    def generate(self, name, prompt, max_new_tokens, eos_id=None,
                 timeout=None):
        """Stream generated tokens from the named generative servable
        (an iterator of ints -- the
        :class:`~mxnet_tpu.serving.decode.GenerationStream`).  Same
        swap-race retry as :meth:`submit`: a hot swap between lookup
        and admit lands the request on the replacement."""
        for _ in range(8):
            s = self.servable(name)
            if not hasattr(s, "generate"):
                raise MXNetError("serving: servable %r (source=%r) is "
                                 "not generative" % (name, s.source))
            try:
                return s.generate(prompt, max_new_tokens,
                                  eos_id=eos_id, timeout=timeout)
            except ServableClosed:
                with self._lock:
                    cur = self._servables.get(name)
                if cur is None or cur is s:
                    raise               # really closed, not swapped
        raise ServableClosed(
            "serving: servable %r kept closing mid-generate (flapping "
            "re-registration?)" % name)

    # -- lifecycle ------------------------------------------------------
    def unregister(self, name, drain=True):
        with self._lock:
            s = self._servables.pop(name, None)
        if s is None:
            raise MXNetError("serving: no servable %r" % name)
        s.close(drain=drain)

    def shutdown(self, drain=True):
        """Close every servable (draining by default) -- the graceful
        process-shutdown path."""
        with self._lock:
            servables = list(self._servables.values())
            self._servables.clear()
        for s in servables:
            s.close(drain=drain)

    def __contains__(self, name):
        with self._lock:
            return name in self._servables

    def __len__(self):
        with self._lock:
            return len(self._servables)
