"""Block/paged KV cache manager (the vLLM idea on bucketing.py's slab
discipline).

The generative engine never allocates per-request device memory: at
construction it carves ONE preallocated per-layer slab pair -- keys and
values, shape ``(layers, num_blocks, block_size, heads, head_dim)`` --
into fixed-size blocks, and a request is admitted by handing it a
**block table** (the ordered list of block ids its tokens map onto).
Token position ``p`` of a request lives at
``(table[p // block_size], p % block_size)``; the decode-step attention
kernel gathers K/V through the table, so sequences share the slabs
without ever being contiguous.

Admission-time sizing is the backpressure contract: a request's whole
budget -- ``prompt_len + max_new_tokens`` -- is allocated **at
admission** and the allocator raises :class:`KVCacheExhausted` when the
free list cannot cover it, so a running sequence can NEVER fail
mid-generation for cache space (the engine maps the exhaustion to the
standard :class:`~mxnet_tpu.serving.batcher.ServingQueueFull` shed).
EOS, max-token completion, cancel and timeout all return blocks through
:meth:`free` -- ``kvcache.blocks_in_use`` returning to zero after a
drain is the leak-proof gate CI holds.

Block 0 is reserved as the **scratch block**: padded decode slots and
padded prefill positions route their writes there (a compiled program
always writes *somewhere*), so it is never handed to a request and its
contents are garbage by design.

Telemetry: ``kvcache.blocks_in_use`` / ``kvcache.fragmentation``
gauges, ``kvcache.allocs`` / ``kvcache.frees`` /
``kvcache.alloc_failures`` counters.
"""
from __future__ import annotations

from ... import sync as _sync
from ... import telemetry as _telemetry
from ...base import MXNetError

__all__ = ["PagedKVCache", "BlockTable", "KVCacheExhausted",
           "SCRATCH_BLOCK"]

# block id 0 is the write sink for padded slots/positions; never
# allocated to a request (see module doc)
SCRATCH_BLOCK = 0


class KVCacheExhausted(MXNetError):
    """Admission-time allocation failed: the free list cannot cover the
    request's ``prompt + max_new`` block budget.  The engine sheds the
    request (ServingQueueFull) -- it is never raised mid-generation."""


class BlockTable:
    """One request's ordered block ids plus its token-capacity bound."""

    __slots__ = ("blocks", "capacity", "freed")

    def __init__(self, blocks, capacity):
        self.blocks = list(blocks)
        self.capacity = int(capacity)   # tokens the table can hold
        self.freed = False

    def __len__(self):
        return len(self.blocks)

    def __repr__(self):
        return "BlockTable(blocks=%r, capacity=%d%s)" % (
            self.blocks, self.capacity, ", freed" if self.freed else "")


class PagedKVCache:
    """Fixed-size block allocator over preallocated per-layer K/V slabs.

    Parameters
    ----------
    layers, heads, head_dim : model geometry of the cached K/V
    block_size : tokens per block
    num_blocks : total blocks in the slab (block 0 is scratch, so the
        allocatable pool is ``num_blocks - 1``)
    dtype : cache dtype
    """

    def __init__(self, layers, heads, head_dim, block_size, num_blocks,
                 dtype="float32"):
        import jax.numpy as jnp
        import numpy as np
        if block_size < 1 or num_blocks < 2:
            raise MXNetError(
                "PagedKVCache needs block_size >= 1 and num_blocks >= 2 "
                "(block 0 is the reserved scratch block), got "
                "block_size=%r num_blocks=%r" % (block_size, num_blocks))
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.dtype = np.dtype(dtype)
        shape = (self.layers, self.num_blocks, self.block_size,
                 self.heads, self.head_dim)
        # THE slabs: functional jax values the compiled prefill/decode
        # programs consume and replace (the engine swaps the references
        # after every step; on TPU donation makes that in-place)
        self.keys = jnp.zeros(shape, self.dtype)
        self.values = jnp.zeros(shape, self.dtype)
        self._lock = _sync.Lock(name="serving.kvcache")
        self._free = list(range(1, self.num_blocks))  # 0 = scratch
        self._used_tokens = {}          # id(table) -> tokens written

    # -- sizing ---------------------------------------------------------
    def blocks_for(self, n_tokens):
        """Blocks needed to hold ``n_tokens`` (ceil)."""
        return max(1, -(-int(n_tokens) // self.block_size))

    @property
    def total_blocks(self):
        """Allocatable pool size (scratch excluded)."""
        return self.num_blocks - 1

    def free_blocks(self):
        with self._lock:
            return len(self._free)

    def blocks_in_use(self):
        with self._lock:
            return self.total_blocks - len(self._free)

    def can_admit(self, n_tokens):
        """Whether :meth:`allocate` for ``n_tokens`` would succeed now
        (admission pre-check; racing admitters still handle the
        exception path)."""
        with self._lock:
            return self.blocks_for(n_tokens) <= len(self._free)

    # -- allocate / free ------------------------------------------------
    def allocate(self, n_tokens):
        """Carve a :class:`BlockTable` holding ``n_tokens`` from the
        free list, or raise :class:`KVCacheExhausted` (counted as
        ``kvcache.alloc_failures``) without partial allocation."""
        need = self.blocks_for(n_tokens)
        with self._lock:
            if need > len(self._free):
                shortfall = (need, len(self._free))
            else:
                blocks = [self._free.pop() for _ in range(need)]
                table = BlockTable(blocks,
                                   capacity=need * self.block_size)
                self._used_tokens[id(table)] = int(n_tokens)
                in_use = self.total_blocks - len(self._free)
                frag = self._fragmentation_locked()
                shortfall = None
        if shortfall is not None:
            if _telemetry._ENABLED:
                _telemetry.hooks.kvcache_alloc_failure()
            raise KVCacheExhausted(
                "kv cache exhausted: need %d blocks for %d tokens, "
                "%d free (of %d)" % (shortfall[0], n_tokens,
                                     shortfall[1], self.total_blocks))
        if _telemetry._ENABLED:
            _telemetry.hooks.kvcache_alloc(in_use, frag)
        return table

    def free(self, table):
        """Return a table's blocks to the free list.  Idempotent -- the
        EOS/timeout/cancel paths may race a drain, and double-freeing a
        block would corrupt a live sequence."""
        with self._lock:
            if table.freed:
                return
            table.freed = True
            self._free.extend(table.blocks)
            self._used_tokens.pop(id(table), None)
            in_use = self.total_blocks - len(self._free)
            frag = self._fragmentation_locked()
        if _telemetry._ENABLED:
            _telemetry.hooks.kvcache_free(in_use, frag)

    # -- introspection --------------------------------------------------
    def _fragmentation_locked(self):
        """Internal fragmentation: share of allocated token slots not
        (yet) holding a token -- admission-time whole-budget allocation
        makes this the honest cost of the shed-never-mid-generation
        contract."""
        in_use = self.total_blocks - len(self._free)
        if in_use == 0:
            return 0.0
        used = sum(self._used_tokens.values())
        return max(0.0, 1.0 - used / float(in_use * self.block_size))

    def note_tokens(self, table, n_tokens):
        """Update the written-token count for ``table`` (fragmentation
        accounting only; capacity is fixed at admission)."""
        with self._lock:
            if not table.freed:
                self._used_tokens[id(table)] = int(n_tokens)

    def stats(self):
        with self._lock:
            in_use = self.total_blocks - len(self._free)
            return {
                "block_size": self.block_size,
                "total_blocks": self.total_blocks,
                "blocks_in_use": in_use,
                "free_blocks": len(self._free),
                "fragmentation": round(self._fragmentation_locked(), 4),
            }

    def padded_table(self, table, width):
        """The table as a fixed-width int32 row for a compiled program:
        real ids first, scratch-block padding after (padded positions
        write into scratch, reads are masked by context length)."""
        import numpy as np
        if len(table.blocks) > width:
            raise MXNetError(
                "block table %d wider than compiled width %d"
                % (len(table.blocks), width))
        row = np.full((width,), SCRATCH_BLOCK, np.int32)
        row[:len(table.blocks)] = table.blocks
        return row

    def __repr__(self):
        return "PagedKVCache(%s)" % (self.stats(),)
