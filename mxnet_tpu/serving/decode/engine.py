"""The autoregressive decode engine: separately-bucketed prefill/decode
AOT executables, continuous batching, token streaming.

The PR-8 engine serves fixed-shape forwards; generation is a *loop*
whose batch membership changes every step.  This module is the loop:

- **executor split**: prefill (whole prompt -> cache blocks + first
  token) compiles once per PROMPT-LENGTH bucket at batch 1; the decode
  step (one token per slot over the paged cache) compiles once per
  SLOT-COUNT bucket.  Both go through the same lower -> fingerprint ->
  :class:`~mxnet_tpu.serving.cache.CompileCache` path as
  ``BucketExecutorPool`` and warm at registration, so no request pays a
  first-compile.
- **continuous batching**: one worker thread runs an admit-then-step
  loop.  Pending requests join the RUNNING batch at a step boundary
  (one prefill call each), finished sequences vacate their slot the
  step they finish, and the live slots pad up to the smallest decode
  bucket -- no bucket flush, no drain-the-batch barrier (Orca's
  iteration-level scheduling).
- **admission backpressure**: the whole ``prompt + max_new`` KV budget
  allocates at submit; an exhausted
  :class:`~.kvcache.PagedKVCache` (or a full pending queue) sheds with
  the standard :class:`~mxnet_tpu.serving.batcher.ServingQueueFull` --
  a running sequence can never die for cache space.
- **token streaming**: :meth:`DecodeEngine.submit` returns a
  :class:`GenerationStream` iterator; every token lands there as it is
  decoded, with ``serving.decode_step`` trace spans recorded as
  children of the request's ``serving.request`` root, so TTFT and
  inter-token latency are product-layer measurements
  (``decode.ttft`` / ``decode.inter_token`` timers).

Hot swap (the PR-12 contract extended mid-decode): re-registering a
:class:`GenerativeServable` installs the replacement for NEW requests
while the old engine's ``close(drain=True)`` keeps stepping its
half-generated sequences to completion -- zero dropped sequences,
counted under ``chaos.survived.serving.decode_swap``.
"""
from __future__ import annotations

import collections
import queue as _queue_mod
import threading
import time

import numpy as np

from ... import chaos as _chaos
from ... import obs as _obs
from ... import sync as _sync
from ... import telemetry as _telemetry
from ...base import MXNetError
from ..batcher import RequestTimeout, ServableClosed, ServingQueueFull
from ..cache import stablehlo_fingerprint
from ..loop import RegistryWatcher as _RegistryWatcher
from .kvcache import SCRATCH_BLOCK, KVCacheExhausted, PagedKVCache

__all__ = ["DecodeEngine", "GenerationStream", "GenerativeServable",
           "GenerativeWatcher"]

_IDLE_WAIT_S = 0.05
_DONE = object()


def _env_buckets(var):
    from ... import env as _env
    spec = _env.get(var)
    try:
        return tuple(sorted({int(tok) for tok in str(spec).split(",")
                             if tok}))
    except ValueError as e:
        raise MXNetError("%s=%r is not a comma-separated int list"
                         % (var, spec)) from e


class GenerationStream:
    """Iterator over one request's generated token ids.

    Tokens arrive as the engine decodes them; iteration blocks until
    the next token, ``StopIteration`` lands after EOS / ``max_new`` /
    cancel / drain, and an engine-side failure re-raises here.
    ``cancel()`` asks the engine to drop the sequence at the next step
    boundary (its cache blocks are freed there)."""

    def __init__(self, model, prompt_len, max_new):
        self.model = model
        self.prompt_len = int(prompt_len)
        self.max_new = int(max_new)
        self._q = _queue_mod.Queue()
        self._error = None
        self._finished = False
        self.finish_reason = None       # eos | length | cancel | closed
        self.cancelled = False
        self.t_submit = time.perf_counter()
        self.t_first_token = None

    # -- engine side ----------------------------------------------------
    def _push(self, token, now):
        if self.t_first_token is None:
            self.t_first_token = now
        self._q.put(int(token))

    def _finish(self, reason, error=None):
        self.finish_reason = reason
        self._error = error
        self._q.put(_DONE)

    # -- client side ----------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        item = self._q.get()
        if item is _DONE:
            self._finished = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def cancel(self):
        """Drop the sequence at the next step boundary (idempotent)."""
        self.cancelled = True

    def tokens(self):
        """Drain the stream to completion and return every token."""
        return list(self)

    @property
    def ttft_s(self):
        """Submit -> first token, or None before the first token."""
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit


class _GenRequest:
    __slots__ = ("prompt", "max_new", "eos_id", "table", "stream",
                 "deadline", "tctx", "generated", "last_token",
                 "t_last_emit", "t_submit")

    def __init__(self, prompt, max_new, eos_id, table, stream, timeout):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.table = table
        self.stream = stream
        self.t_submit = stream.t_submit
        self.deadline = (self.t_submit + timeout) if timeout else None
        self.tctx = None
        self.generated = 0
        self.last_token = None
        self.t_last_emit = None

    @property
    def position(self):
        """Cache position the NEXT decode step writes (the last
        generated token's index in the full sequence)."""
        return len(self.prompt) + self.generated - 1


class _AotPrograms:
    """lower -> fingerprint -> CompileCache -> compile, per static
    shape key (the BucketExecutorPool discipline generalized to
    multi-argument decode/prefill signatures)."""

    def __init__(self, cache=None, label="decode"):
        self._cache = cache
        self._label = label
        self._programs = {}
        self.fingerprints = {}

    def build(self, key, fn, specs):
        import jax
        if key in self._programs:
            return self._programs[key]
        jfn = jax.jit(fn)
        lowered = jfn.lower(*specs)
        fp = stablehlo_fingerprint(lowered.as_text())
        call = None
        if self._cache is not None:
            exported = self._cache.get(fp)
            if exported is not None:
                call = jax.jit(exported.call)
        if call is None:
            call = lowered.compile()
            if self._cache is not None:
                try:
                    from jax import export as jexport
                    self._cache.put(fp, jexport.export(jfn)(*specs))
                except Exception:
                    pass        # a cold next process, not an error now
        self._programs[key] = call
        self.fingerprints[key] = fp
        return call

    def get(self, key):
        return self._programs[key]


class DecodeEngine:
    """Continuous-batching autoregressive decode over a paged KV cache.

    Parameters
    ----------
    model : :class:`~.model.TinyGPT`-shaped spec (``prefill_kv`` /
        ``decode_logits`` / geometry attributes)
    params : flat name -> device-array dict
    prefill_buckets : prompt-length buckets (each compiles one prefill
        executable at batch 1)
    decode_buckets : slot-count buckets (each compiles one decode-step
        executable); the largest is the concurrent-sequence bound
    block_size / num_blocks : :class:`~.kvcache.PagedKVCache` geometry
    max_queue : pending-request bound past which submits shed
    cache : :class:`~mxnet_tpu.serving.cache.CompileCache` or None
    """

    def __init__(self, model, params, prefill_buckets=None,
                 decode_buckets=None, block_size=None, num_blocks=None,
                 max_queue=None, cache=None, label="generative",
                 kv_dtype="float32"):
        from ... import env as _env
        self.model = model
        self.params = params
        self._label = label
        if prefill_buckets is None:
            prefill_buckets = _env_buckets(
                "MXNET_TPU_SERVING_PREFILL_BUCKETS")
        if decode_buckets is None:
            decode_buckets = _env_buckets(
                "MXNET_TPU_SERVING_DECODE_BUCKETS")
        self.prefill_buckets = tuple(sorted(set(
            int(b) for b in prefill_buckets)))
        self.decode_buckets = tuple(sorted(set(
            int(b) for b in decode_buckets)))
        if not self.prefill_buckets or self.prefill_buckets[0] < 1 \
                or not self.decode_buckets \
                or self.decode_buckets[0] < 1:
            raise MXNetError("decode engine: buckets must be positive "
                             "ints, got prefill=%r decode=%r"
                             % (prefill_buckets, decode_buckets))
        # buckets past the model's context are uncompilable dead
        # weight (the default env list serves models of any size):
        # keep those that fit, plus one capped at max_seq so the
        # longest admissible prompt stays servable
        if self.prefill_buckets[-1] > model.max_seq:
            kept = tuple(b for b in self.prefill_buckets
                         if b < model.max_seq)
            self.prefill_buckets = kept + (int(model.max_seq),)
        block_size = int(block_size if block_size is not None
                         else _env.get("MXNET_TPU_SERVING_KV_BLOCK"))
        num_blocks = int(num_blocks if num_blocks is not None
                         else _env.get("MXNET_TPU_SERVING_KV_BLOCKS"))
        self.cache = PagedKVCache(model.num_layers, model.num_heads,
                                  model.head_dim, block_size,
                                  num_blocks, dtype=kv_dtype)
        # fixed compiled block-table width: enough for the longest
        # sequence the model can hold
        self.max_blocks_per_seq = self.cache.blocks_for(model.max_seq)
        self.max_queue = int(max_queue if max_queue is not None
                             else _env.get("MXNET_TPU_SERVING_QUEUE"))
        self.max_slots = self.decode_buckets[-1]
        self._programs = _AotPrograms(cache=cache, label=label)
        self._cond = _sync.Condition(name="serving.decode")
        self._pending = collections.deque()
        self._active = []
        self._closed = False
        self._drain = True
        self._drained_live = 0      # sequences in flight at close()
        self._thread = None

    # -- AOT build ------------------------------------------------------
    def _prefill_impl(self, params, kv_k, kv_v, tokens, table,
                      true_len):
        import jax.numpy as jnp
        bs = self.cache.block_size
        logits, ks, vs = self.model.prefill_kv(params, tokens)
        lb = tokens.shape[1]
        pos = jnp.arange(lb, dtype=jnp.int32)
        blk = jnp.where(pos < true_len,
                        jnp.take(table, pos // bs), SCRATCH_BLOCK)
        off = pos % bs
        kv_k = kv_k.at[:, blk, off].set(ks.astype(kv_k.dtype))
        kv_v = kv_v.at[:, blk, off].set(vs.astype(kv_v.dtype))
        last = jnp.take(logits[0], true_len - 1, axis=0)
        first_token = jnp.argmax(last).astype(jnp.int32)
        return first_token, kv_k, kv_v

    def _decode_impl(self, params, kv_k, kv_v, tokens, positions,
                     tables):
        next_token, _logits, kv_k, kv_v = self.model.decode_logits(
            params, kv_k, kv_v, tokens, positions, tables,
            self.cache.block_size)
        return next_token, kv_k, kv_v

    def _specs(self):
        import jax
        i32 = np.int32
        kv = jax.ShapeDtypeStruct(self.cache.keys.shape,
                                  self.cache.keys.dtype)
        pspec = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for n, v in self.params.items()}
        mb = self.max_blocks_per_seq
        prefill = {
            b: (pspec, kv, kv,
                jax.ShapeDtypeStruct((1, b), i32),
                jax.ShapeDtypeStruct((mb,), i32),
                jax.ShapeDtypeStruct((), i32))
            for b in self.prefill_buckets}
        decode = {
            s: (pspec, kv, kv,
                jax.ShapeDtypeStruct((s,), i32),
                jax.ShapeDtypeStruct((s,), i32),
                jax.ShapeDtypeStruct((s, mb), i32))
            for s in self.decode_buckets}
        return prefill, decode

    def warmup(self):
        """Compile every prefill and decode bucket (compile-cache
        checked first); returns total warm-up seconds.  After this no
        request can trigger a compile."""
        t0 = time.perf_counter()
        prefill, decode = self._specs()
        for b, specs in prefill.items():
            self._programs.build(("prefill", b), self._prefill_impl,
                                 specs)
        for s, specs in decode.items():
            self._programs.build(("decode", s), self._decode_impl,
                                 specs)
        dt = time.perf_counter() - t0
        if _telemetry._ENABLED:
            _telemetry.hooks.serving_warmup(
                self._label, dt,
                len(self.prefill_buckets) + len(self.decode_buckets))
        return dt

    def _bucket(self, buckets, n, what):
        for b in buckets:
            if b >= n:
                return b
        raise MXNetError("decode engine: %s of %d exceeds the largest "
                         "%s bucket %d" % (what, n, what, buckets[-1]))

    # -- intake ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens, eos_id=None, timeout=None):
        """Admit one generation request; returns a
        :class:`GenerationStream`.

        The FULL ``prompt + max_new_tokens`` cache budget allocates
        here -- :class:`ServingQueueFull` is raised when the pending
        queue is at capacity or the KV cache cannot cover the budget
        (``decode.shed`` + ``kvcache.alloc_failures``), so an accepted
        request can never fail for cache space mid-generation."""
        prompt = [int(t) for t in prompt]
        max_new = int(max_new_tokens)
        if not prompt or max_new < 1:
            raise MXNetError("generate needs a non-empty prompt and "
                             "max_new_tokens >= 1")
        if len(prompt) > self.prefill_buckets[-1]:
            raise MXNetError(
                "prompt of %d tokens exceeds the largest prefill "
                "bucket %d" % (len(prompt), self.prefill_buckets[-1]))
        total = len(prompt) + max_new
        if total > self.model.max_seq:
            raise MXNetError(
                "prompt + max_new_tokens = %d exceeds model max_seq %d"
                % (total, self.model.max_seq))
        with self._cond:
            if self._closed:
                raise ServableClosed("generative servable %r is closed"
                                     % self._label)
            if len(self._pending) >= self.max_queue:
                if _telemetry._ENABLED:
                    _telemetry.hooks.decode_shed(self._label, "queue")
                raise ServingQueueFull(
                    "generative servable %r pending queue full (%d)"
                    % (self._label, self.max_queue))
            try:
                table = self.cache.allocate(total)
            except KVCacheExhausted as e:
                if _telemetry._ENABLED:
                    _telemetry.hooks.decode_shed(self._label,
                                                 "kvcache")
                raise ServingQueueFull(
                    "generative servable %r shed at admission: %s"
                    % (self._label, e)) from e
            stream = GenerationStream(self._label, len(prompt),
                                      max_new)
            req = _GenRequest(prompt, max_new, eos_id, table, stream,
                              timeout)
            if _obs._TRACE_ENABLED:
                req.tctx = _obs.trace.fresh_context()
            self._pending.append(req)
            depth = len(self._pending)
            self._cond.notify()
        if _telemetry._ENABLED:
            _telemetry.hooks.decode_request(self._label, depth)
        return stream

    # -- the loop -------------------------------------------------------
    def start(self):
        if self._thread is not None:
            raise MXNetError("DecodeEngine already started")
        self._thread = threading.Thread(
            target=self._worker, daemon=True,
            name="mxtpu-decode-%s" % self._label)
        self._thread.start()

    def _worker(self):
        while True:
            with self._cond:
                while not self._pending and not self._active \
                        and not self._closed:
                    self._cond.wait(_IDLE_WAIT_S)
                if self._closed:
                    if not self._drain:
                        self._abort_locked()
                        return
                    if not self._pending and not self._active:
                        return
            self._admit()
            if self._active:
                self._step()

    def _abort_locked(self):
        """close(drain=False): resolve everything as closed, free every
        table -- still zero *lost* streams, they all end explicitly."""
        err = ServableClosed("generative servable %r closed without "
                             "drain" % self._label)
        for req in list(self._pending) + self._active:
            self.cache.free(req.table)
            req.stream._finish("closed", error=err)
        self._pending.clear()
        del self._active[:]

    def _admit(self):
        """Step-boundary admission: pending requests take free slots in
        the RUNNING batch (one prefill each).  Expired/cancelled
        requests resolve here and never occupy a slot."""
        while True:
            with self._cond:
                if not self._pending \
                        or len(self._active) >= self.max_slots:
                    return
                req = self._pending.popleft()
            now = time.perf_counter()
            if req.stream.cancelled:
                self._finish(req, "cancel")
                continue
            if req.deadline is not None and now > req.deadline:
                self.cache.free(req.table)
                req.stream._finish("timeout", error=RequestTimeout(
                    "generation waited %.1fms > timeout while queued"
                    % (1e3 * (now - req.t_submit))))
                if _telemetry._ENABLED:
                    _telemetry.hooks.serving_timeout(self._label)
                continue
            self._prefill(req)

    def _prefill(self, req):
        import jax
        bucket = self._bucket(self.prefill_buckets, len(req.prompt),
                              "prefill")
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(req.prompt)] = req.prompt
        table = self.cache.padded_table(req.table,
                                        self.max_blocks_per_seq)
        t0 = time.perf_counter()
        call = self._programs.get(("prefill", bucket))
        try:
            _chaos.fail_point("serving.decode.prefill",
                              model=self._label, bucket=bucket)
            first, kv_k, kv_v = call(
                self.params, self.cache.keys, self.cache.values,
                tokens, table, np.int32(len(req.prompt)))
            first = int(jax.device_get(first))
        except Exception as e:
            if _telemetry._ENABLED:
                _telemetry.hooks.serving_error(self._label)
            self.cache.free(req.table)
            req.stream._finish("error", error=e)
            return
        self.cache.keys, self.cache.values = kv_k, kv_v
        self.cache.note_tokens(req.table, len(req.prompt) + 1)
        now = time.perf_counter()
        if _telemetry._ENABLED:
            _telemetry.hooks.decode_prefill(self._label, bucket,
                                            len(req.prompt), now - t0)
            _telemetry.hooks.decode_ttft(now - req.t_submit)
        self._emit(req, first, t0, now)
        if not self._maybe_finish(req):
            self._active.append(req)

    def _step(self):
        """ONE decode iteration for every live slot."""
        import jax
        n = len(self._active)
        bucket = self._bucket(self.decode_buckets, n, "decode")
        tokens = np.zeros((bucket,), np.int32)
        positions = np.zeros((bucket,), np.int32)
        tables = np.full((bucket, self.max_blocks_per_seq),
                         SCRATCH_BLOCK, np.int32)
        for i, req in enumerate(self._active):
            tokens[i] = req.last_token
            positions[i] = req.position
            tables[i] = self.cache.padded_table(
                req.table, self.max_blocks_per_seq)
        t0 = time.perf_counter()
        call = self._programs.get(("decode", bucket))
        try:
            _chaos.fail_point("serving.decode.step", model=self._label,
                              occupancy=n, bucket=bucket)
            out, kv_k, kv_v = call(self.params, self.cache.keys,
                                   self.cache.values, tokens,
                                   positions, tables)
            out = jax.device_get(out)
        except Exception as e:
            if _telemetry._ENABLED:
                _telemetry.hooks.serving_error(self._label)
            for req in self._active:
                self.cache.free(req.table)
                req.stream._finish("error", error=e)
            del self._active[:]
            return
        self.cache.keys, self.cache.values = kv_k, kv_v
        now = time.perf_counter()
        if _telemetry._ENABLED:
            _telemetry.hooks.decode_step(self._label, n, bucket,
                                         now - t0)
        finished = []
        for i, req in enumerate(self._active):
            self._emit(req, int(out[i]), t0, now)
            self.cache.note_tokens(req.table,
                                   len(req.prompt) + req.generated)
            if self._maybe_finish(req):
                finished.append(req)
        if finished:
            # finished sequences vacate their slot IMMEDIATELY: the
            # next iteration packs the survivors into a smaller bucket
            self._active = [r for r in self._active
                            if r not in finished]

    def _emit(self, req, token, t_step0, now):
        req.generated += 1
        req.last_token = token
        if _telemetry._ENABLED and req.t_last_emit is not None:
            _telemetry.hooks.decode_inter_token(now - req.t_last_emit)
        if _obs._TRACE_ENABLED and req.tctx is not None:
            _obs.record_span(
                "serving.decode_step", req.tctx.child(),
                parent_id=req.tctx.span_id, t0=t_step0,
                dur=now - t_step0,
                attrs={"model": self._label,
                       "token_index": req.generated - 1})
        req.t_last_emit = now
        req.stream._push(token, now)

    def _maybe_finish(self, req):
        if req.stream.cancelled:
            self._finish(req, "cancel")
            return True
        if req.eos_id is not None and req.last_token == req.eos_id:
            self._finish(req, "eos")
            return True
        if req.generated >= req.max_new:
            self._finish(req, "length")
            return True
        return False

    def _finish(self, req, reason):
        self.cache.free(req.table)
        now = time.perf_counter()
        if _obs._TRACE_ENABLED and req.tctx is not None:
            _obs.record_span(
                "serving.request", req.tctx, t0=req.t_submit,
                dur=now - req.t_submit,
                attrs={"model": self._label, "generative": True,
                       "tokens": req.generated, "reason": reason})
        if _telemetry._ENABLED:
            _telemetry.hooks.decode_finish(self._label, reason,
                                           req.generated)
            _telemetry.hooks.serving_latency(now - req.t_submit)
        req.stream._finish(reason)

    # -- introspection --------------------------------------------------
    def queue_depth(self):
        with self._cond:
            return len(self._pending)

    def active_sequences(self):
        with self._cond:
            return len(self._active)

    def live_sequences(self):
        with self._cond:
            return len(self._pending) + len(self._active)

    def fingerprint(self, kind, bucket):
        return self._programs.fingerprints.get((kind, bucket))

    # -- lifecycle ------------------------------------------------------
    def close(self, drain=True):
        """Stop intake and shut the loop down.  ``drain=True`` keeps
        STEPPING until every admitted sequence runs to completion (the
        mid-decode hot-swap path rides this); ``drain=False`` resolves
        everything as closed.  Returns the number of sequences that
        were in flight when close was called."""
        with self._cond:
            if self._closed:
                return 0
            self._closed = True
            self._drain = drain
            live = len(self._pending) + len(self._active)
            self._drained_live = live
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        return live

    @property
    def closed(self):
        return self._closed


class GenerativeServable:
    """One deployed generative model: a :class:`DecodeEngine` behind
    the registry's servable surface (lookup / drain / introspection
    compatible with :class:`~mxnet_tpu.serving.registry.Servable`)."""

    source = "generative"

    def __init__(self, name, engine):
        self.name = name
        self._engine = engine

    # -- client surface -------------------------------------------------
    def generate(self, prompt, max_new_tokens, eos_id=None,
                 timeout=None):
        """Stream greedy-decoded tokens for ``prompt``; returns a
        :class:`GenerationStream`."""
        return self._engine.submit(prompt, max_new_tokens,
                                   eos_id=eos_id, timeout=timeout)

    # -- introspection --------------------------------------------------
    @property
    def engine(self):
        return self._engine

    @property
    def buckets(self):
        return self._engine.decode_buckets

    @property
    def prefill_buckets(self):
        return self._engine.prefill_buckets

    def queue_depth(self):
        return self._engine.queue_depth()

    @property
    def queue_capacity(self):
        return self._engine.max_queue

    def kvcache_stats(self):
        return self._engine.cache.stats()

    @property
    def closed(self):
        return self._engine.closed

    def close(self, drain=True):
        return self._engine.close(drain=drain)

    def __repr__(self):
        return ("GenerativeServable(%r, prefill=%r, decode=%r, kv=%s)"
                % (self.name, self._engine.prefill_buckets,
                   self._engine.decode_buckets,
                   self._engine.cache.stats()))


class GenerativeWatcher(_RegistryWatcher):
    """The :class:`~mxnet_tpu.serving.loop.RegistryWatcher` contract
    for generative servables: same verified-step discovery, same
    retry/backoff/failure-budget state machine (it IS a
    RegistryWatcher), but a swap re-registers through
    ``register_generative`` -- params restored from the checkpoint's
    ``params`` item -- and the old engine drains its half-generated
    sequences to completion (zero dropped, counted under
    ``chaos.survived.serving.decode_swap``)."""

    def __init__(self, registry, name, checkpoint, model, **kwargs):
        # block/input_shape/dtype are fixed-shape-servable concepts;
        # the base class only threads them into register(), which
        # _register_step replaces wholesale
        super().__init__(registry, name, checkpoint, block=None,
                         input_shape=(), **kwargs)
        self.model = model

    def _register_step(self, step):
        self.registry.register_generative(
            self.name, model=self.model, checkpoint=self.manager,
            step=step, **self._register_kwargs)
