"""A GPT-style decoder in pure-function form for the generative engine.

The training-side transformer stack (``gluon/nn/transformer.py``) is an
encoder: full-sequence forwards, no cache.  Autoregressive serving
needs the SAME weights runnable in two compiled shapes -- a **prefill**
(whole prompt, causal, emits every position's K/V) and a **decode
step** (one token per slot, attending over the paged cache) -- so the
model here is a plain params-dict + pure functions, the
``fn(params, x)`` shape every servable source already lands in:

- :meth:`TinyGPT.full_logits` -- the reference full causal forward
  (pre-LN blocks, GELU MLP, tied unembedding); also the single-shot
  numerics oracle :meth:`reference_decode` loops over.
- :meth:`TinyGPT.prefill_kv` -- the same forward, additionally
  returning every layer's per-position K/V so the engine can scatter
  the prompt into cache blocks inside ONE compiled program.
- :meth:`TinyGPT.decode_logits` -- one token per slot: project q/k/v,
  scatter the new K/V into the slot's block-table position, attend over
  the paged cache through the ``paged_attention`` kernel-registry entry.

Everything is fp32-accumulated and greedy-decodable: the engine's
continuous-batching tests hold decode tokens bit-identical between a
solo run and a join-mid-batch run, which per-slot row-independent math
(layernorm, per-head attention, row-wise matmul) preserves.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

__all__ = ["TinyGPT", "tiny_gpt"]


class TinyGPT:
    """Decoder-only transformer spec: geometry + pure functions.

    Parameters live OUTSIDE the object (a flat ``{name: jnp array}``
    dict from :meth:`init_params` or a checkpoint restore), so hot-swap
    re-registration is just "same TinyGPT, new dict".
    """

    def __init__(self, vocab_size=128, units=32, num_layers=2,
                 num_heads=2, max_seq=64, ffn_mult=4):
        if units % num_heads:
            raise MXNetError("TinyGPT: units %d not divisible by heads "
                             "%d" % (units, num_heads))
        self.vocab_size = int(vocab_size)
        self.units = int(units)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = self.units // self.num_heads
        self.max_seq = int(max_seq)
        self.ffn = int(ffn_mult) * self.units
        self.scale = 1.0 / float(np.sqrt(self.head_dim))

    # -- params ---------------------------------------------------------
    def init_params(self, seed=0):
        """Flat name->array dict (embedding tied to the unembedding)."""
        import jax
        import jax.numpy as jnp
        key = jax.random.PRNGKey(seed)
        p = {}

        def nrm(key, shape, scale):
            return (jax.random.normal(key, shape, jnp.float32) * scale)

        ks = jax.random.split(key, 2 + 4 * self.num_layers)
        p["embed"] = nrm(ks[0], (self.vocab_size, self.units), 0.08)
        p["pos_embed"] = nrm(ks[1], (self.max_seq, self.units), 0.02)
        for i in range(self.num_layers):
            k0, k1, k2, k3 = ks[2 + 4 * i: 6 + 4 * i]
            pre = "h%d_" % i
            p[pre + "ln1_g"] = jnp.ones((self.units,), jnp.float32)
            p[pre + "ln1_b"] = jnp.zeros((self.units,), jnp.float32)
            p[pre + "wqkv"] = nrm(k0, (self.units, 3 * self.units),
                                  0.08)
            p[pre + "wo"] = nrm(k1, (self.units, self.units), 0.08)
            p[pre + "ln2_g"] = jnp.ones((self.units,), jnp.float32)
            p[pre + "ln2_b"] = jnp.zeros((self.units,), jnp.float32)
            p[pre + "w1"] = nrm(k2, (self.units, self.ffn), 0.08)
            p[pre + "b1"] = jnp.zeros((self.ffn,), jnp.float32)
            p[pre + "w2"] = nrm(k3, (self.ffn, self.units), 0.08)
            p[pre + "b2"] = jnp.zeros((self.units,), jnp.float32)
        p["lnf_g"] = jnp.ones((self.units,), jnp.float32)
        p["lnf_b"] = jnp.zeros((self.units,), jnp.float32)
        return p

    # -- shared pieces --------------------------------------------------
    @staticmethod
    def _ln(x, g, b):
        import jax.numpy as jnp
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * g + b

    @staticmethod
    def _gelu(x):
        import jax
        return jax.nn.gelu(x, approximate=True)

    def _mlp(self, p, pre, x):
        import jax.numpy as jnp
        h = self._gelu(jnp.dot(x, p[pre + "w1"]) + p[pre + "b1"])
        return jnp.dot(h, p[pre + "w2"]) + p[pre + "b2"]

    def _split_heads(self, t):
        # (..., units) -> (..., heads, head_dim)
        return t.reshape(t.shape[:-1]
                         + (self.num_heads, self.head_dim))

    # -- full causal forward (reference + prefill) ----------------------
    def _forward(self, params, tokens, collect_kv):
        import jax.numpy as jnp
        b, t = tokens.shape
        x = jnp.take(params["embed"], tokens, axis=0) \
            + params["pos_embed"][:t][None]
        causal = jnp.tril(jnp.ones((t, t), bool))
        kvs = []
        for i in range(self.num_layers):
            pre = "h%d_" % i
            h = self._ln(x, params[pre + "ln1_g"], params[pre + "ln1_b"])
            qkv = jnp.dot(h, params[pre + "wqkv"])
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = self._split_heads(q)               # (b, t, H, D)
            k = self._split_heads(k)
            v = self._split_heads(v)
            if collect_kv:
                kvs.append((k, v))
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * self.scale
            s = jnp.where(causal[None, None], s, -1e30)
            w = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
            w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True),
                                1e-30)
            att = jnp.einsum("bhqk,bkhd->bqhd", w, v)
            att = att.reshape(b, t, self.units)
            x = x + jnp.dot(att, params[pre + "wo"])
            h2 = self._ln(x, params[pre + "ln2_g"],
                          params[pre + "ln2_b"])
            x = x + self._mlp(params, pre, h2)
        x = self._ln(x, params["lnf_g"], params["lnf_b"])
        logits = jnp.dot(x, params["embed"].T)     # tied unembedding
        return (logits, kvs) if collect_kv else logits

    def full_logits(self, params, tokens):
        """Reference causal forward: tokens (b, t) int32 -> logits
        (b, t, vocab)."""
        return self._forward(params, tokens, collect_kv=False)

    def prefill_kv(self, params, tokens):
        """tokens (1, t) -> (logits (1, t, vocab), keys, values) with
        keys/values stacked per layer: (layers, t, heads, head_dim)."""
        import jax.numpy as jnp
        logits, kvs = self._forward(params, tokens, collect_kv=True)
        ks = jnp.stack([k[0] for k, _v in kvs])    # (L, t, H, D)
        vs = jnp.stack([v[0] for _k, v in kvs])
        return logits, ks, vs

    # -- decode step over the paged cache -------------------------------
    def decode_logits(self, params, kv_keys, kv_values, token_ids,
                      positions, block_tables, block_size):
        """One decode step for a slot batch.

        token_ids (s,) int32; positions (s,) int32 (where each new
        token is written, = its context length - 1); kv slabs (layers,
        num_blocks, block_size, heads, head_dim); block_tables (s,
        max_blocks) int32.  Returns (next_token (s,) int32, logits
        (s, vocab), kv_keys', kv_values').
        """
        import jax.numpy as jnp
        from ...kernels.paged_attention import paged_attention
        s = token_ids.shape[0]
        blk = jnp.take_along_axis(
            block_tables, (positions // block_size)[:, None],
            axis=1)[:, 0]                           # (s,)
        off = positions % block_size
        ctx = (positions + 1).astype(jnp.int32).reshape(s, 1)
        x = jnp.take(params["embed"], token_ids, axis=0) \
            + jnp.take(params["pos_embed"], positions, axis=0)
        for i in range(self.num_layers):
            pre = "h%d_" % i
            h = self._ln(x, params[pre + "ln1_g"], params[pre + "ln1_b"])
            qkv = jnp.dot(h, params[pre + "wqkv"])
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = self._split_heads(q)                # (s, H, D)
            k = self._split_heads(k)
            v = self._split_heads(v)
            # scatter the new token's K/V into its cache position;
            # padded slots carry all-scratch tables so their writes
            # land in the reserved scratch block
            kv_keys = kv_keys.at[i, blk, off].set(
                k.astype(kv_keys.dtype))
            kv_values = kv_values.at[i, blk, off].set(
                v.astype(kv_values.dtype))
            att = paged_attention(q, kv_keys[i], kv_values[i],
                                  block_tables, ctx, scale=self.scale)
            att = att.reshape(s, self.units).astype(x.dtype)
            x = x + jnp.dot(att, params[pre + "wo"])
            h2 = self._ln(x, params[pre + "ln2_g"],
                          params[pre + "ln2_b"])
            x = x + self._mlp(params, pre, h2)
        x = self._ln(x, params["lnf_g"], params["lnf_b"])
        logits = jnp.dot(x, params["embed"].T)
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return next_token, logits, kv_keys, kv_values

    # -- single-shot oracle ---------------------------------------------
    def reference_decode(self, params, prompt, max_new_tokens,
                         eos_id=None):
        """Greedy single-shot decode: one FULL forward per token, no
        cache -- the numerics oracle the engine's tokens are gated
        against (CI ``serving_decode`` stage)."""
        import jax.numpy as jnp
        tokens = [int(t) for t in prompt]
        out = []
        for _ in range(int(max_new_tokens)):
            logits = self.full_logits(
                params, jnp.asarray([tokens], jnp.int32))
            nxt = int(jnp.argmax(logits[0, -1]))
            out.append(nxt)
            tokens.append(nxt)
            if eos_id is not None and nxt == eos_id:
                break
        return out

    def __repr__(self):
        return ("TinyGPT(vocab=%d, units=%d, layers=%d, heads=%d, "
                "max_seq=%d)" % (self.vocab_size, self.units,
                                 self.num_layers, self.num_heads,
                                 self.max_seq))


def tiny_gpt(vocab_size=128, units=32, num_layers=2, num_heads=2,
             max_seq=64):
    """The CI/test-sized GPT-style decoder."""
    return TinyGPT(vocab_size=vocab_size, units=units,
                   num_layers=num_layers, num_heads=num_heads,
                   max_seq=max_seq)
