"""``mxnet_tpu.serving.decode`` -- the generative serving tier.

Autoregressive decoding is a different serving problem from the
fixed-shape forwards the PR-8 tier batches: each request is a LOOP
whose cost is unknown upfront (EOS-dependent), whose KV cache grows
every step, and whose latency contract is per-token (TTFT + inter-token),
not per-request.  This package is that tier:

- :class:`~.kvcache.PagedKVCache` -- fixed-size blocks carved from
  preallocated per-layer slabs; a per-request block table maps token
  position -> (block, offset), so sequences grow without contiguous
  reallocation and memory fragments at worst one partial block per
  sequence (``kvcache.*`` telemetry).
- :class:`~.engine.DecodeEngine` -- prefill and decode as SEPARATELY
  bucketed AOT executables (prompt-length vs slot-count) with
  continuous batching: requests join the running batch at step
  boundaries, finished sequences vacate immediately, admission sheds
  (``ServingQueueFull``) when the cache cannot cover a request's whole
  ``prompt + max_new`` budget -- never mid-generation.
- :class:`~.engine.GenerativeServable` /
  :meth:`ModelRegistry.register_generative` /
  :meth:`ModelRegistry.generate` -- the multi-tenant surface:
  token-streaming iterators, mid-decode hot swap with drain-to-
  completion on the old executables, ``/statusz`` + ``/healthz``
  integration.
- :class:`~.model.TinyGPT` -- a GPT-style decoder in pure-function
  form (prefill + paged decode step + full-forward oracle), the
  CI/bench workload.

The decode-step attention itself is a kernel-registry citizen
(``kernels.paged_attention``): a Pallas online-softmax walk over the
slot's block table on TPU (interpret mode on CPU under
``MXNET_TPU_KERNELS=1``), an XLA gather+masked-softmax fallback
everywhere else.  docs/serving.md covers tuning.
"""
from .engine import (DecodeEngine, GenerationStream, GenerativeServable,
                     GenerativeWatcher)
from .kvcache import BlockTable, KVCacheExhausted, PagedKVCache
from .model import TinyGPT, tiny_gpt

__all__ = ["BlockTable", "DecodeEngine", "GenerationStream",
           "GenerativeServable", "GenerativeWatcher",
           "KVCacheExhausted", "PagedKVCache", "TinyGPT", "tiny_gpt"]
