"""Per-bucket AOT executor pool.

A servable forward is one pure function ``fn(params, x) -> tuple(outs)``
over a *fixed* per-bucket batch shape.  At registration the pool lowers
and compiles one executable per padded-shape bucket (checking the
persistent :class:`~mxnet_tpu.serving.cache.CompileCache` first) and
runs each once on zeros -- so by the time a request can reach the
batcher, every shape class it can dispatch is already compiled and no
request ever pays a first-compile.

The compiled executables are registered with ``mx.profiling``'s store
(when capture is armed), so serving programs show up in ``mxprof
report`` and the sharding sanitizer's collective contract like any
training step.
"""
from __future__ import annotations

import time

import numpy as np

from .. import telemetry as _telemetry
from ..base import MXNetError
from .cache import stablehlo_fingerprint

__all__ = ["BucketExecutorPool"]


class BucketExecutorPool:
    """AOT-compiled executables over padded batch buckets.

    Parameters
    ----------
    pure_fn : callable ``(params_dict, x) -> tuple(jax arrays)``
    params : dict name -> device array, fed to every call
    input_shape : per-sample shape (no batch dim)
    dtype : input dtype
    buckets : ascending batch-size buckets; requests pad to the
        smallest bucket that fits
    cache : CompileCache or None
    label : provenance label for profiling capture
    """

    def __init__(self, pure_fn, params, input_shape, dtype, buckets,
                 cache=None, label="servable"):
        self._fn = pure_fn
        self._params = params
        self.input_shape = tuple(int(s) for s in input_shape)
        self.dtype = np.dtype(dtype)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise MXNetError("serving: buckets must be positive ints, "
                             "got %r" % (buckets,))
        self._cache = cache
        self._label = label
        self._compiled = {}       # bucket -> callable(params, x)
        self._fingerprints = {}   # bucket -> fingerprint
        self._num_outputs = None

    @property
    def max_bucket(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        """Smallest bucket that holds ``n`` samples."""
        for b in self.buckets:
            if b >= n:
                return b
        raise MXNetError("serving: batch of %d exceeds the largest "
                         "bucket %d" % (n, self.max_bucket))

    def compiled_buckets(self):
        return sorted(self._compiled)

    def fingerprint(self, bucket):
        return self._fingerprints.get(bucket)

    # -- build ----------------------------------------------------------
    def warmup(self):
        """Compile every bucket and execute each once on zeros; returns
        total warm-up seconds.  After this no request shape class can
        trigger a compile."""
        import jax
        t0 = time.perf_counter()
        zeros = {b: np.zeros((b,) + self.input_shape, self.dtype)
                 for b in self.buckets}
        for b in self.buckets:
            call = self._build(b)
            outs = call(self._params, zeros[b])
            jax.block_until_ready(outs)
            if self._num_outputs is None:
                self._num_outputs = len(outs)
        dt = time.perf_counter() - t0
        if _telemetry._ENABLED:
            _telemetry.hooks.serving_warmup(self._label, dt,
                                            len(self.buckets))
        return dt

    def hbm_plan(self, device_hbm_bytes=None):
        """Predict peak HBM per bucket (``analysis.memory.hbm_plan``):
        two real compiles anchored at the smallest bucket fit the
        const+per-item line, every bucket is extrapolated along it, and
        ``largest_fit_bucket`` answers what ``device_hbm_bytes`` can
        actually serve.  Compiles hit jax's executable cache when the
        buckets are already warm."""
        import jax
        from ..analysis import memory as _memory
        pspecs = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for n, v in self._params.items()}
        b0 = self.buckets[0]
        xspec = jax.ShapeDtypeStruct((b0,) + self.input_shape,
                                     self.dtype)
        return _memory.hbm_plan(
            "serving:%s" % self._label,
            device_hbm_bytes=device_hbm_bytes, buckets=self.buckets,
            batch_size=b0, fn=jax.jit(self._fn),
            args=(pspecs, xspec))

    def _build(self, bucket):
        import jax
        if bucket in self._compiled:
            return self._compiled[bucket]
        pspecs = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for n, v in self._params.items()}
        xspec = jax.ShapeDtypeStruct((bucket,) + self.input_shape,
                                     self.dtype)
        jfn = jax.jit(self._fn)
        lowered = jfn.lower(pspecs, xspec)
        key = stablehlo_fingerprint(lowered.as_text())
        call = None
        if self._cache is not None:
            exported = self._cache.get(key)
            if exported is not None:
                # cache hit: the portable artifact replaces re-tracing;
                # jit-wrap so XLA compiles it once (persistent XLA cache
                # makes that compile itself warm across processes)
                call = jax.jit(exported.call)
        if call is None:
            call = lowered.compile()
            if self._cache is not None:
                try:
                    from jax import export as jexport
                    self._cache.put(key,
                                    jexport.export(jfn)(pspecs, xspec))
                except Exception:
                    pass        # a cold next process, not an error now
        self._compiled[bucket] = call
        self._fingerprints[bucket] = key
        self._register_profiling(bucket, jfn, (pspecs, xspec))
        return call

    def _register_profiling(self, bucket, jfn, specs):
        from .. import profiling as _profiling
        if not _profiling._ENABLED:
            return
        from ..profiling import store as _store
        _store.register("serving:%s:b%d" % (self._label, bucket),
                        "serving:%s:b%d" % (self._label, bucket),
                        jfn, specs, kind="serving")

    # -- dispatch -------------------------------------------------------
    def call(self, bucket, x):
        """Run the ``bucket`` executable on a host/device batch ``x``
        (already padded to the bucket).  Returns the output tuple."""
        call = self._compiled.get(bucket)
        if call is None:           # unregistered bucket: compile lazily
            call = self._build(bucket)
        return call(self._params, x)

    @property
    def num_outputs(self):
        return self._num_outputs
