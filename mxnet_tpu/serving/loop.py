"""The always-on loop: continuous training publishing checkpoints, and
a registry watcher hot-swapping the servable (ISSUE 12 tentpole).

Every pillar of a production train->serve loop already existed --
atomic manifest-verified checkpoints, a draining ModelRegistry, warmup
pre-compile, the persistent compile cache -- and nothing composed them.
This module is the composition:

- :class:`ContinuousTrainer` runs the training loop and **publishes**
  the (block, trainer) state every ``publish_every`` steps through
  ``CheckpointManager.save_training`` -- the same atomic commit path
  everything else uses, so a kill mid-publish can never tear what the
  watcher sees;
- :class:`RegistryWatcher` polls the checkpoint root, discovers a new
  **verified** step via ``CheckpointManager.latest_step()`` (the
  corruption-tolerant, quarantining discovery -- a torn newest step
  reads as "previous good step", which IS the rollback), and hot-swaps
  the servable by re-registering it: the new executor pool warms while
  the old servable keeps serving, then the registry installs the new
  one and drains the old -- zero dropped (non-shed) requests across
  the swap, proven under chaos in ``tests/test_chaos.py``.

A swap that aborts (chaos, a raced retention delete, a compile
failure) retries with exponential backoff; a step that exhausts its
retries is marked bad and skipped -- the previous model keeps serving
-- and ``failure_budget`` consecutive failed steps suspend the watcher
with a warning (operator intervention beats flapping forever).
"""
from __future__ import annotations

import threading
import time
import warnings

from .. import obs as _obs
from .. import sync as _sync
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..checkpoint import CheckpointManager

__all__ = ["ContinuousTrainer", "RegistryWatcher"]


def _manager(checkpoint):
    return checkpoint if isinstance(checkpoint, CheckpointManager) \
        else CheckpointManager(checkpoint)


class ContinuousTrainer:
    """Train continuously and publish checkpoints for a serving watcher.

    ::

        ct = ContinuousTrainer(net, trainer, loss_fn, batch_fn,
                               manager, publish_every=50)
        ct.resume()                # restore newest intact step, if any
        ct.start()                 # background loop (or run_steps(n))
        ...
        ct.close()

    ``data`` is either a fixed ``(x, y)`` pair or a callable
    ``step -> (x, y)``.  ``handler`` (a ``preemption.PreemptionHandler``)
    is polled at every loop boundary so SIGTERM lands a consistent save
    and stops the loop.  The publish path is
    ``CheckpointManager.save_training`` -- atomic commit, manifest
    last -- so the watcher can never observe a half-written step as
    loadable.

    Multi-process runs (ISSUE 15): the loop beats this rank's liveness
    lease every step (``distributed.beat_lease`` -- what barrier
    attribution reads to call a missing rank *presumed dead*;
    single-process pays one attribute check, nothing else), and
    ``on_publish_error`` sets the policy when a sharded publish aborts
    on a rank failure: ``"raise"`` (default) surfaces the typed error
    -- the exit the elastic restart supervisor restarts the world on
    -- while ``"continue"`` warns and trains past the failed publish
    (the abort already swept its staging and counted
    ``checkpoint.commit_aborted``).
    """

    def __init__(self, block, trainer, loss_fn, data, manager,
                 publish_every=1, handler=None, on_publish_error="raise"):
        self.block = block
        self.trainer = trainer
        self.loss_fn = loss_fn
        self._data = data
        self.manager = _manager(manager)
        self.publish_every = int(publish_every)
        if self.publish_every < 1:
            raise MXNetError("ContinuousTrainer: publish_every must be "
                             ">= 1, got %r" % publish_every)
        if on_publish_error not in ("raise", "continue"):
            raise MXNetError("ContinuousTrainer: on_publish_error must "
                             "be 'raise' or 'continue', got %r"
                             % (on_publish_error,))
        self._on_publish_error = on_publish_error
        from ..distributed import lease_beater
        self._lease_beat = lease_beater()   # None single-process
        self.handler = handler
        self._lock = _sync.Lock(name="serving.train_loop")
        self._stop = _sync.Event(name="serving.train_loop.stop")
        self._thread = None
        self._step = 0
        self._published_step = None
        self._error = None
        _obs.status.register_trainer(self)   # weak: /statusz heartbeat

    # -- state ----------------------------------------------------------
    @property
    def step(self):
        with self._lock:
            return self._step

    @property
    def published_step(self):
        with self._lock:
            return self._published_step

    def resume(self):
        """Restore the newest intact checkpoint (or start fresh);
        returns the Checkpoint or None.  The step counter continues
        from the restored step -- the crash-restart contract."""
        ckpt = self.manager.restore_training(self.block, self.trainer)
        with self._lock:
            self._step = ckpt.step if ckpt is not None else 0
            self._published_step = ckpt.step if ckpt is not None else None
        return ckpt

    # -- the loop -------------------------------------------------------
    def run_steps(self, n):
        """Run ``n`` training steps inline (the thread-free surface the
        scenarios and tests drive); publishes at every
        ``publish_every`` boundary.  Returns the last loss (or None if
        stopped before a step ran)."""
        from .. import autograd
        last = None
        for _ in range(int(n)):
            if self._stop.is_set():
                break
            if self.handler is not None and self.handler.triggered:
                # the triggered read already wrote the preemption save
                break
            with self._lock:
                self._step += 1
                step = self._step
            _sp = _obs.begin_span("train.step", step=step) \
                if _obs._TRACE_ENABLED else None
            try:
                x, y = self._data(step) if callable(self._data) \
                    else self._data
                from ..analysis import memory as _memory
                from ..analysis import numerics as _numerics
                from .. import chaos as _chaos
                # numerics.nonfinite chaos point: poison THIS batch so
                # the fault flows through forward/backward and the
                # sentinel (not the injector) must catch it
                _box = {}
                _chaos.fail_point("numerics.nonfinite", box=_box,
                                  step=step)
                # memory.leak chaos point: the armed action pins device
                # arrays in a hidden list, so the LEAK SENTINEL (not
                # the injector) must catch the live-bytes growth
                _chaos.fail_point("memory.leak", step=step)
                if _box.get("poison"):
                    x = _numerics.poison_nd(x)
                with autograd.record():
                    loss = self.loss_fn(self.block(x), y)
                loss.backward()
                if _numerics.check_enabled():
                    # ONE fused finite check over the named gradient
                    # set; raises NonFiniteError(param, step, kind)
                    # naming the first offender BEFORE the optimizer
                    # applies the poisoned update
                    _numerics.finite_sentinel(
                        [(p.name, p._data._grad)
                         for p in self.trainer._params
                         if p._data is not None
                         and p._data._grad is not None],
                        step=step)
                self.trainer.step(x.shape[0])
                last = loss
                if step % self.publish_every == 0:
                    self.publish()
            finally:
                if _sp is not None:
                    _obs.end_span(_sp)
            if _obs._GOODPUT_ENABLED:
                # one ledger tick per training step: windows close at
                # the MXNET_TPU_OBS_GOODPUT_WINDOW boundary and the
                # attribution publishes through goodput.* instruments
                _obs.goodput.ledger().step()
            if _memory.watch_enabled():
                # one sentinel tick per step: live-array censuses run
                # only at window boundaries, inside the sentinel
                _memory.sentinel().step()
            # liveness beat for /statusz: a stale heartbeat means a
            # wedged loop even when every thread is technically alive
            _obs.status.heartbeat()
            if self._lease_beat is not None:
                # the cross-process twin: the coordination-KV lease
                # barrier attribution reads to presume a rank dead
                self._lease_beat()
        return last

    def publish(self):
        """Checkpoint the current (block, trainer) state as the current
        step, through the atomic commit path."""
        with self._lock:
            step = self._step
        t0 = time.perf_counter()
        _sp = _obs.begin_span("train.publish", step=step) \
            if _obs._TRACE_ENABLED else None
        try:
            self.manager.save_training(step, self.block, self.trainer,
                                       metadata={"step": step})
        except Exception as e:
            from ..distributed import RankFailure
            if self._on_publish_error == "continue" \
                    and isinstance(e, RankFailure):
                # the abort already swept its staging and counted
                # checkpoint.commit_aborted; the previous published
                # step keeps serving and training goes on
                warnings.warn(
                    "publish of step %d aborted on a rank failure "
                    "(%s); continuing past it" % (step, e),
                    RuntimeWarning, stacklevel=2)
                return None
            raise
        finally:
            if _sp is not None:
                _obs.end_span(_sp)
        with self._lock:
            self._published_step = step
        if _obs._GOODPUT_ENABLED:
            # the ledger's publish guard: the checkpoint_stall spike
            # this window is expected work, not a regression
            _obs.goodput.ledger().note_publish()
        from ..analysis import memory as _memory
        if _memory.watch_enabled():
            # same guard for the leak sentinel: the snapshot's
            # live-bytes spike is expected work, not a leak
            _memory.sentinel().note_publish()
        if _telemetry._ENABLED:
            _telemetry.hooks.train_publish(step,
                                           time.perf_counter() - t0)
        return step

    # -- lifecycle ------------------------------------------------------
    def start(self, max_steps=None):
        """Run the loop on a background thread until :meth:`stop` (or
        ``max_steps`` steps, or a preemption trigger)."""
        if self._thread is not None:
            raise MXNetError("ContinuousTrainer already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, args=(max_steps,), daemon=True,
            name="mxtpu-train-loop")
        self._thread.start()

    def _run(self, max_steps):
        try:
            if max_steps is not None:
                self.run_steps(max_steps)
            else:
                while not self._stop.is_set():
                    if self.run_steps(1) is None:
                        break           # preempted/stopped mid-boundary
        except Exception as e:          # surface at close(), not a dead
            with self._lock:            # daemon thread
                self._error = e

    def stop(self):
        self._stop.set()

    def close(self):
        """Stop the loop, join the thread, drain any in-flight async
        checkpoint write, and re-raise a loop error if one occurred."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        self.manager.wait_until_finished()
        if _obs._GOODPUT_ENABLED:
            # close the partial tail window so a short run still
            # reports its attribution
            _obs.goodput.ledger().flush(reason="close")
        from ..analysis import memory as _memory
        if _memory.watch_enabled():
            # close the sentinel's partial tail window too
            _memory.sentinel().flush()
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err


class RegistryWatcher:
    """Watch a checkpoint root and hot-swap a servable to each new
    verified step.

    ::

        w = RegistryWatcher(reg, "model", ckpt_root, block,
                            input_shape=(8,), buckets=(1, 4))
        w.poll_once()          # or w.start() for the background loop
        ...
        w.close()

    Discovery reuses ``CheckpointManager.latest_step()``: manifest +
    CRC verification with quarantine, so a step torn by a killed
    trainer is renamed ``<step>.corrupt`` and the watcher keeps (or
    rolls back to) the previous verified step.  A swap re-registers the
    servable: the replacement warms (AOT per-bucket compile --
    a persistent-compile-cache hit for unchanged shapes) while the old
    servable still serves, then the registry installs it and drains the
    old one -- no accepted request is dropped.  Swap failures retry
    with exponential backoff (``swap_retries``/``swap_backoff_s``);
    a step exhausting its retries is skipped (``bad_steps()``) and
    ``failure_budget`` consecutive bad steps suspend the watcher.
    """

    def __init__(self, registry, name, checkpoint, block, input_shape,
                 dtype="float32", poll_s=None, swap_retries=None,
                 swap_backoff_s=None, failure_budget=None,
                 **register_kwargs):
        from .. import env as _env
        self.registry = registry
        self.name = name
        self.manager = _manager(checkpoint)
        self.block = block
        self.input_shape = tuple(input_shape)
        self.dtype = dtype
        self._register_kwargs = register_kwargs
        self.poll_s = float(poll_s if poll_s is not None
                            else _env.get("MXNET_TPU_SERVING_POLL_S"))
        self._swap_retries = int(
            swap_retries if swap_retries is not None
            else _env.get("MXNET_TPU_SERVING_SWAP_RETRIES"))
        self._swap_backoff_s = float(
            swap_backoff_s if swap_backoff_s is not None
            else _env.get("MXNET_TPU_SERVING_SWAP_BACKOFF_S"))
        self._failure_budget = int(
            failure_budget if failure_budget is not None
            else _env.get("MXNET_TPU_SERVING_SWAP_BUDGET"))
        self._lock = _sync.Lock(name="serving.watcher")
        self._stop = _sync.Event(name="serving.watcher.stop")
        self._thread = None
        self._served_step = None
        self._bad_steps = set()
        self._consecutive_failures = 0
        self._suspended = False
        _obs.status.register_watcher(self)   # weak: /healthz readiness

    # -- state ----------------------------------------------------------
    @property
    def served_step(self):
        with self._lock:
            return self._served_step

    @property
    def suspended(self):
        """True once ``failure_budget`` consecutive steps failed to
        swap -- the watcher stops flapping and keeps serving the last
        good model until an operator intervenes."""
        with self._lock:
            return self._suspended

    def bad_steps(self):
        """Steps that exhausted their swap retries and are skipped."""
        with self._lock:
            return sorted(self._bad_steps)

    # -- one poll -------------------------------------------------------
    def poll_once(self):
        """Discover the newest verified step and swap to it if it is
        newer than what is serving.  Returns the newly served step, or
        None when nothing changed (no new step, step already bad, or
        the swap failed and the previous model keeps serving)."""
        _sp = _obs.begin_span("serving.watcher.discover",
                              model=self.name) \
            if _obs._TRACE_ENABLED else None
        step = None
        try:
            step = self.manager.latest_step()
        finally:
            if _sp is not None:
                _obs.end_span(_sp, step=step)
        if step is None:
            return None
        with self._lock:
            if self._suspended or step in self._bad_steps:
                return None
            served = self._served_step
        if served is not None and step <= served:
            return None
        return self._swap(step)

    def _swap(self, step):
        _sp = _obs.begin_span("serving.swap", model=self.name,
                              step=step) \
            if _obs._TRACE_ENABLED else None
        try:
            return self._swap_attempts(step)
        finally:
            if _sp is not None:
                _obs.end_span(_sp)

    def _register_step(self, step):
        """ONE registration attempt for ``step`` -- the overridable
        point subclasses (the generative watcher) replace to route a
        swap through a different registry surface while inheriting the
        whole retry/backoff/failure-budget state machine."""
        self.registry.register(
            self.name, block=self.block, checkpoint=self.manager,
            step=step, input_shape=self.input_shape,
            dtype=self.dtype, **self._register_kwargs)

    def _swap_attempts(self, step):
        from .. import chaos as _chaos
        t0 = time.perf_counter()
        attempts = self._swap_retries + 1
        last_err = None
        for attempt in range(1, attempts + 1):
            if attempt > 1:
                # exponential backoff, interruptible by close()
                if self._stop.wait(self._swap_backoff_s
                                   * (2 ** (attempt - 2))):
                    return None
            try:
                self._register_step(step)
            except Exception as e:
                last_err = e
                if _telemetry._ENABLED:
                    _telemetry.hooks.serving_swap(
                        self.name, step, time.perf_counter() - t0,
                        ok=False, attempt=attempt, error=str(e))
                continue
            with self._lock:
                prev, self._served_step = self._served_step, step
                self._consecutive_failures = 0
            if _telemetry._ENABLED:
                _telemetry.hooks.serving_swap(
                    self.name, step, time.perf_counter() - t0, ok=True,
                    from_step=prev, attempt=attempt)
            if attempt > 1:
                _chaos.survived("serving.swap", "retry")
            return step
        # retries exhausted: skip this step, keep serving the previous
        # verified one (the failure-budget rollback contract)
        with self._lock:
            self._bad_steps.add(step)
            self._consecutive_failures += 1
            exhausted = self._consecutive_failures >= self._failure_budget
            if exhausted:
                self._suspended = True
            served = self._served_step
        _chaos.survived("serving.swap", "rollback")
        if exhausted and _telemetry._ENABLED:
            # terminal, alertable: the watcher stops flapping here and
            # nothing will retry until an operator acts -- /healthz
            # reports NOT_READY off the same state
            _telemetry.hooks.serving_watcher_suspended(
                self.name, step, self._failure_budget)
        warnings.warn(
            "serving watcher %r: swap to step %d failed after %d "
            "attempt(s) (%s); still serving step %r%s"
            % (self.name, step, attempts, last_err, served,
               "; failure budget exhausted, watcher suspended"
               if exhausted else ""),
            RuntimeWarning, stacklevel=3)
        return None

    # -- lifecycle ------------------------------------------------------
    def start(self):
        """Poll on a background thread every ``poll_s`` seconds until
        :meth:`close` (or suspension by the failure budget)."""
        if self._thread is not None:
            raise MXNetError("RegistryWatcher already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, daemon=True,
            name="mxtpu-watcher-%s" % self.name)
        self._thread.start()

    def _watch(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:     # discovery must outlive weather
                warnings.warn("serving watcher %r: poll failed: %s"
                              % (self.name, e), RuntimeWarning)
            if self.suspended:
                return
            self._stop.wait(self.poll_s)

    def close(self):
        """Stop polling and join the watcher thread (the servable stays
        registered; close it through the registry)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
