"""Serving tier: compiled, dynamically-batched inference (ISSUE 8).

The north star names "heavy traffic from millions of users"; this
subsystem is the user-facing half -- it composes the existing pillars
into an inference engine:

- **model registry** (``registry.py``): multi-tenant name -> servable
  store loading from checkpoint manifests (PR 3), ``symbol+params``,
  or ONNX (including third-party protobufs);
- **executor pool** (``executor.py``): one AOT-compiled executable per
  padded batch bucket, warmed at registration (no request pays a
  first-compile), behind a persistent on-disk compile cache keyed on
  the PR-6 normalized-HLO fingerprint (``cache.py``);
- **dynamic batcher** (``batcher.py``): a ``sync``-disciplined bounded
  request queue assembling micro-batches under a ``max_wait`` deadline,
  padding to the nearest bucket, dispatching one compiled call and
  scattering responses -- with per-request timeouts, queue-full
  load-shedding, and graceful drain on shutdown;
- **SLO telemetry**: ``serving.*`` instruments (request latency with
  p50/p95/p99, QPS, batch occupancy, queue depth, shed/timeout counts)
  through ``mx.telemetry``, summarized by the CLI's ``serving``
  section; ``bench.py::bench_serving`` emits the latency-vs-QPS curve;
- **the generative tier** (``decode/``): autoregressive token
  streaming -- prefill and decode as separately bucketed AOT
  executables, a paged KV cache (fixed-size blocks + per-request block
  tables), continuous batching (join at step boundaries, vacate on
  finish, shed at admission when no blocks are free), mid-decode hot
  swap with drain-to-completion, and the ``paged_attention`` kernel
  walking the block table;
- **the always-on loop** (``loop.py``): ``ContinuousTrainer`` publishes
  atomic checkpoints while ``RegistryWatcher`` discovers each new
  *verified* step and hot-swaps the servable with zero dropped
  requests (drain-then-replace, warm pre-compile, retry/backoff under
  a failure budget) -- proven under the chaos harness
  (``mx.chaos``, docs/chaos.md); ``bench.py::bench_serving_hotswap``
  records swap latency and p99-during-swap.

::

    reg = mx.serving.ModelRegistry()
    reg.register("resnet", onnx="resnet50.onnx",
                 input_shape=(3, 224, 224))
    y = reg.infer("resnet", img)           # batched with other callers
    reg.shutdown(drain=True)

Tuning knobs (``docs/serving.md``): ``MXNET_TPU_SERVING_BUCKETS``,
``MXNET_TPU_SERVING_MAX_WAIT_MS``, ``MXNET_TPU_SERVING_QUEUE``,
``MXNET_TPU_SERVING_CACHE_DIR``.
"""
from __future__ import annotations

from .batcher import (DynamicBatcher, RequestTimeout, ServableClosed,
                      ServingQueueFull)
from .cache import CompileCache, stablehlo_fingerprint
from .decode import (DecodeEngine, GenerationStream, GenerativeServable,
                     GenerativeWatcher, KVCacheExhausted, PagedKVCache,
                     TinyGPT, tiny_gpt)
from .executor import BucketExecutorPool
from .loop import ContinuousTrainer, RegistryWatcher
from .registry import ModelRegistry, Servable

__all__ = [
    "ModelRegistry", "Servable", "DynamicBatcher", "BucketExecutorPool",
    "CompileCache", "stablehlo_fingerprint",
    "ContinuousTrainer", "RegistryWatcher",
    "ServingQueueFull", "RequestTimeout", "ServableClosed",
    "DecodeEngine", "GenerationStream", "GenerativeServable",
    "GenerativeWatcher", "KVCacheExhausted", "PagedKVCache",
    "TinyGPT", "tiny_gpt",
]
