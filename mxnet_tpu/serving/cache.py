"""Persistent on-disk compile cache for serving executables.

Each per-bucket servable program is AOT-lowered at registration; the
lowered StableHLO text, normalized the same way ``profiling/cost.py``
normalizes compiled HLO (module name and source-location metadata
stripped), fingerprints the program.  The serialized ``jax.export``
artifact is committed under that fingerprint, so the *next* process
that registers the same model/bucket deserializes a portable program
instead of re-tracing Python -- and, stacked on the framework-wide
persistent XLA compilation cache (``MXNET_TPU_COMPILATION_CACHE``),
its warm-up compile is served from disk too.

Artifacts are committed through ``checkpoint.core.atomic_write_bytes``
(tmp+fsync+rename), so a process killed mid-store can never leave a
truncated artifact where a loadable one would be trusted.
"""
from __future__ import annotations

import os
import re

from .. import telemetry as _telemetry

__all__ = ["CompileCache", "stablehlo_fingerprint"]

# StableHLO normalization: jax stamps every op line with a loc(#locN)
# reference and appends a #locN = loc("file":line:col) table; the module
# name carries the traced function's name.  None of those affect the
# program, all of them vary across processes/refactors.
_LOC_REF = re.compile(r"\s*loc\(#loc\d*\)")
_LOC_DEF = re.compile(r"^#loc\d*\s*=\s*loc\(.*\)\s*$", re.MULTILINE)
_LOC_BARE = re.compile(r"^#loc\s*=\s*loc\(.*\)\s*$", re.MULTILINE)
_MODULE = re.compile(r"^module @\S+", re.MULTILINE)


def stablehlo_fingerprint(text):
    """Stable identity of a lowered (StableHLO) program -- the PR-6
    normalized-HLO fingerprint applied at the serving layer: locations
    and the module name are normalized away, then the profiling
    subsystem's fingerprint hashes the rest."""
    from ..profiling.cost import fingerprint
    norm = _LOC_REF.sub("", text)
    norm = _LOC_DEF.sub("", norm)
    norm = _LOC_BARE.sub("", norm)
    norm = _MODULE.sub("module @<norm>", norm)
    return fingerprint(norm)


def default_cache_dir():
    from .. import env as _env
    return os.path.expanduser(_env.get("MXNET_TPU_SERVING_CACHE_DIR"))


class CompileCache:
    """Fingerprint-keyed store of serialized ``jax.export`` artifacts.

    ``get(key)`` returns the deserialized ``Exported`` (or None);
    ``put(key, exported)`` commits its serialization atomically.  A
    corrupt or version-incompatible artifact reads as a miss, never an
    error -- the cache can only ever cost a recompile.
    """

    def __init__(self, root=None):
        self.root = os.fspath(root) if root else default_cache_dir()
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key):
        return os.path.join(self.root, key + ".mxe")

    def get(self, key):
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
            from jax import export as jexport
            exported = jexport.deserialize(blob)
        except Exception:
            self._record(hit=False)
            return None
        self._record(hit=True)
        return exported

    def put(self, key, exported):
        from ..checkpoint.core import atomic_write_bytes
        try:
            atomic_write_bytes(self._path(key), exported.serialize())
        except Exception:
            return None
        return self._path(key)

    def __contains__(self, key):
        return os.path.exists(self._path(key))

    @staticmethod
    def _record(hit):
        if _telemetry._ENABLED:
            _telemetry.hooks.serving_compile_cache(hit)
