"""Global random state.

Reference: ``python/mxnet/random.py :: seed`` and the per-device RNG
resources of ``src/resource.cc :: ResourceManager``.  TPU-native design: a
single counter-based ``jax.random`` key stream.  Eager op calls split a
fresh subkey per call; hybridized graphs receive the key as an explicit
traced input (so a compiled step function stays pure and reproducible).
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()


def _get_key():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
    return _state.key


def seed(seed_state, ctx="all"):
    """Seed the global stream (reference: ``mx.random.seed``)."""
    _state.key = jax.random.PRNGKey(int(seed_state))


class _TracedStream:
    """Key stream used while tracing a hybridized graph: subkeys split
    from an explicit traced key input, so the compiled function stays pure
    and gets fresh randomness each call (the key is an argument)."""

    def __init__(self, key):
        self.key = key

    def next(self):
        self.key, sub = jax.random.split(self.key)
        return sub


def traced_stream(key):
    """Context manager installing a traced key stream (hybridize tracer)."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        prev = getattr(_state, "stream", None)
        _state.stream = _TracedStream(key)
        try:
            yield _state.stream
        finally:
            _state.stream = prev
    return _cm()


def next_key():
    """Split and return a fresh subkey (one per stateful-rng op call)."""
    stream = getattr(_state, "stream", None)
    if stream is not None:
        return stream.next()
    key = _get_key()
    _state.key, sub = jax.random.split(key)
    return sub


def current_key():
    return _get_key()
