"""``mxlint`` / ``python -m mxnet_tpu.analysis`` -- the one CLI over
all three analysis passes.

Exit status: 1 when any error-severity diagnostic survives suppression
(warnings too under ``--strict``), else 0 -- so CI gates on the exit
code and consumes ``--json`` for reporting.
"""
from __future__ import annotations

import argparse
import sys
from typing import List

from .core import (ERROR, RULES, Diagnostic, render_human, render_json)

__all__ = ["main"]

# what ``--self`` lints: the package plus everything CI byte-compiles
SELF_PATHS = ("mxnet_tpu", "examples", "tools", "benchmark", "bench.py",
              "__graft_entry__.py")


def _build_parser():
    ap = argparse.ArgumentParser(
        prog="mxlint",
        description="Static graph checker + trace-safety linter + "
                    "retrace auditor for mxnet_tpu (docs/analysis.md).")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to trace-lint")
    ap.add_argument("--self", dest="self_check", action="store_true",
                    help="lint the repository itself (%s) and run the "
                         "retrace audit -- the CI lint gate"
                         % " ".join(SELF_PATHS))
    ap.add_argument("--graph", action="append", default=[],
                    metavar="SYMBOL_JSON",
                    help="run the static graph checker over a saved "
                         "-symbol.json (repeatable)")
    ap.add_argument("--shape", action="append", default=[],
                    metavar="NAME=SHAPE",
                    help="input shape for --graph checking, e.g. "
                         "data=1,3,224,224 (repeatable)")
    ap.add_argument("--retrace", action="store_true",
                    help="audit registry op params against the "
                         "hybridize cache key")
    ap.add_argument("--disable", default="", metavar="RULES",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every registered rule and exit")
    return ap


def _parse_shapes(specs) -> dict:
    shapes = {}
    for spec in specs:
        name, _, dims = spec.partition("=")
        shapes[name] = tuple(int(d) for d in dims.split(",") if d)
    return shapes


def _list_rules() -> str:
    lines = []
    for r in sorted(RULES.values(), key=lambda r: (r.kind, r.id)):
        lines.append("%-20s %-9s %-8s %s"
                     % (r.id, r.kind, r.severity, r.doc))
    return "\n".join(lines)


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    # importing the passes registers their rules
    from . import graph_check, retrace, trace_lint

    if args.list_rules:
        print(_list_rules())
        return 0

    ignore = set(filter(None, args.disable.split(",")))
    diags: List[Diagnostic] = []

    paths = list(args.paths)
    run_retrace = args.retrace
    if args.self_check:
        import os
        paths.extend(p for p in SELF_PATHS if os.path.exists(p))
        run_retrace = True

    if paths:
        diags.extend(trace_lint.lint_paths(paths, ignore=ignore))

    for gpath in args.graph:
        from ..symbol import load as sym_load
        from ..base import MXNetError
        try:
            sym = sym_load(gpath)
        except (MXNetError, OSError, ValueError, KeyError) as e:
            diags.append(Diagnostic("graph-load",
                                    "cannot load %s: %s" % (gpath, e),
                                    file=gpath, line=0))
            continue
        for d in graph_check.check_symbol(
                sym, shapes=_parse_shapes(args.shape), ignore=ignore):
            d.file = gpath
            diags.append(d)

    if run_retrace:
        diags.extend(d for d in retrace.audit_retrace()
                     if d.rule not in ignore)

    if not paths and not args.graph and not run_retrace:
        _build_parser().print_usage()
        return 2

    print(render_json(diags) if args.as_json else render_human(diags))
    failing = [d for d in diags
               if d.severity == ERROR or args.strict]
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
