"""``mxlint`` / ``python -m mxnet_tpu.analysis`` -- the one CLI over
all the analysis passes.

Exit status: 1 when any error-severity diagnostic survives suppression
(warnings too under ``--strict``), else 0 -- so CI gates on the exit
code and consumes ``--json`` for reporting.

Incremental mode (ISSUE 5 satellite): ``--changed`` lints only files
``git diff`` names (worktree vs HEAD, falling back to the last commit),
and ``--baseline snapshot.json`` suppresses findings recorded by a
previous ``--write-baseline`` run -- so pre-commit and the CI lint
stage stay fast and quiet as the rule count grows, while ``--self``
remains the authoritative full gate.
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
from typing import List

from .core import (ERROR, RULES, Diagnostic, render_human, render_json)

__all__ = ["main"]

# what ``--self`` lints: the package plus everything CI byte-compiles
SELF_PATHS = ("mxnet_tpu", "examples", "tools", "benchmark", "bench.py",
              "__graft_entry__.py")


def _build_parser():
    ap = argparse.ArgumentParser(
        prog="mxlint",
        description="Static graph checker + trace-safety linter + "
                    "concurrency sanitizer + sharding sanitizer + "
                    "perf linter + numerics sanitizer + memory "
                    "sanitizer + retrace auditor for mxnet_tpu "
                    "(docs/analysis.md, docs/sharding.md, "
                    "docs/perf_lint.md, docs/numerics.md, "
                    "docs/memory.md).")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to lint")
    ap.add_argument("--self", dest="self_check", action="store_true",
                    help="lint the repository itself (%s) and run the "
                         "retrace audit -- the full CI lint gate"
                         % " ".join(SELF_PATHS))
    ap.add_argument("--changed", action="store_true",
                    help="lint only files `git diff --name-only` "
                         "reports (worktree vs HEAD, else the last "
                         "commit); lock-order analysis still builds "
                         "the full-tree graph but reports only into "
                         "changed files")
    ap.add_argument("--baseline", metavar="JSON",
                    help="suppress findings recorded in this snapshot "
                         "(see --write-baseline)")
    ap.add_argument("--write-baseline", metavar="JSON",
                    help="write surviving findings as a baseline "
                         "snapshot and exit 0")
    ap.add_argument("--graph", action="append", default=[],
                    metavar="SYMBOL_JSON",
                    help="run the static graph checker over a saved "
                         "-symbol.json (repeatable)")
    ap.add_argument("--shape", action="append", default=[],
                    metavar="NAME=SHAPE",
                    help="input shape for --graph checking, e.g. "
                         "data=1,3,224,224 (repeatable)")
    ap.add_argument("--retrace", action="store_true",
                    help="audit registry op params against the "
                         "hybridize cache key")
    ap.add_argument("--collective-diff", nargs=2,
                    metavar=("BASELINE", "CURRENT"),
                    help="diff two collective-contract JSONs (written "
                         "by analysis.sharding.save_contract) and fail "
                         "on unblessed GSPMD collectives -- the CI "
                         "shardlint gate (docs/sharding.md)")
    ap.add_argument("--perf-diff", nargs=2,
                    metavar=("BASELINE", "CURRENT"),
                    help="diff two perf-audit JSONs (written by "
                         "analysis.perf.save_audit) and fail on grown "
                         "transpose/unfused/pad-waste shares or "
                         "unblessed advisories -- the CI perflint "
                         "gate (docs/perf_lint.md)")
    ap.add_argument("--numerics-diff", nargs=2,
                    metavar=("BASELINE", "CURRENT"),
                    help="diff two numerics-audit JSONs (written by "
                         "analysis.numerics.save_audit) and fail on "
                         "grown half-accum-dot/convert-storm/"
                         "half-reduce shares or unblessed advisories "
                         "-- the CI numlint gate (docs/numerics.md)")
    ap.add_argument("--memory-diff", nargs=2,
                    metavar=("BASELINE", "CURRENT"),
                    help="diff two memory-audit JSONs (written by "
                         "analysis.memory.save_audit) and fail on "
                         "grown peak HBM or unblessed executables/"
                         "advisories -- the CI memlint gate "
                         "(docs/memory.md)")
    ap.add_argument("--sarif", metavar="OUT",
                    help="also write surviving findings (every pass) "
                         "as a SARIF 2.1.0 log for CI annotation; "
                         "exit-code contract unchanged")
    ap.add_argument("--disable", default="", metavar="RULES",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every registered rule and exit")
    return ap


def _parse_shapes(specs) -> dict:
    shapes = {}
    for spec in specs:
        name, _, dims = spec.partition("=")
        shapes[name] = tuple(int(d) for d in dims.split(",") if d)
    return shapes


def _list_rules() -> str:
    lines = []
    for r in sorted(RULES.values(), key=lambda r: (r.kind, r.id)):
        lines.append("%-22s %-9s %-8s %s"
                     % (r.id, r.kind, r.severity, r.doc))
    return "\n".join(lines)


def _git_changed_files() -> List[str]:
    """Python files the working tree changed vs HEAD; when the tree is
    clean (CI on a fresh checkout), the files of the last commit."""
    def run(*args):
        try:
            out = subprocess.run(["git"] + list(args),
                                 capture_output=True, text=True,
                                 timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return []
        if out.returncode != 0:
            return []
        return [ln.strip() for ln in out.stdout.splitlines() if ln.strip()]

    files = run("diff", "--name-only", "HEAD")
    files += run("ls-files", "--others", "--exclude-standard")
    if not files:
        # a clean tree (CI on a fresh checkout): the last commit's
        # files; diff-tree also handles the root commit
        files = run("diff-tree", "--no-commit-id", "--name-only", "-r",
                    "--root", "HEAD")
    import os
    return sorted({f for f in files
                   if f.endswith(".py") and os.path.exists(f)})


def _baseline_key(d: Diagnostic) -> tuple:
    # line numbers shift on unrelated edits; (rule, file, message) is
    # stable across them
    return (d.rule, d.file or "", d.message)


def _load_baseline(path):
    with open(path) as f:
        data = json.load(f)
    return {(rec["rule"], rec.get("file") or "", rec["message"])
            for rec in data.get("findings", [])}


def _write_baseline(path, diags: List[Diagnostic]):
    recs = [{"rule": d.rule, "file": d.file, "message": d.message}
            for d in diags]
    with open(path, "w") as f:
        json.dump({"format": 1, "findings": recs}, f, indent=2,
                  sort_keys=True)
        f.write("\n")


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    # importing the passes registers their rules
    from . import (concurrency, graph_check, memory, numerics, perf,
                   retrace, sharding, trace_lint)

    if args.list_rules:
        print(_list_rules())
        return 0

    ignore = set(filter(None, args.disable.split(",")))
    diags: List[Diagnostic] = []

    paths = list(args.paths)
    run_retrace = args.retrace
    report_files = None
    if args.self_check:
        import os
        paths.extend(p for p in SELF_PATHS if os.path.exists(p))
        run_retrace = True
    if args.changed:
        import os
        changed = _git_changed_files()
        # inside this repo, scope to what --self lints (tests are not
        # gated); in a foreign tree every changed .py file counts
        if not paths and any(os.path.exists(p) for p in SELF_PATHS):
            changed = [f for f in changed
                       if any(f == p
                              or f.startswith(p.rstrip("/") + "/")
                              for p in SELF_PATHS)]
        paths.extend(changed)
        # the order graph needs the WHOLE tree to catch a cycle whose
        # other half lives in an unchanged file; reporting stays scoped
        report_files = set(changed)

    if paths:
        diags.extend(trace_lint.lint_paths(paths, ignore=ignore))
        conc_paths = paths
        if report_files is not None:
            import os
            conc_paths = [p for p in SELF_PATHS if os.path.exists(p)]
        diags.extend(concurrency.audit_lock_order(
            conc_paths, ignore=ignore, report_files=report_files))
        # mesh-axis declarations span files the same way lock-order
        # edges do: scan the whole tree, report into the scoped set
        diags.extend(sharding.audit_sharding(
            conc_paths, ignore=ignore, report_files=report_files))

    for gpath in args.graph:
        from ..symbol import load as sym_load
        from ..base import MXNetError
        try:
            sym = sym_load(gpath)
        except (MXNetError, OSError, ValueError, KeyError) as e:
            diags.append(Diagnostic("graph-load",
                                    "cannot load %s: %s" % (gpath, e),
                                    file=gpath, line=0))
            continue
        for d in graph_check.check_symbol(
                sym, shapes=_parse_shapes(args.shape), ignore=ignore):
            d.file = gpath
            diags.append(d)

    if run_retrace:
        diags.extend(d for d in retrace.audit_retrace()
                     if d.rule not in ignore)

    if args.collective_diff:
        base_path, cur_path = args.collective_diff
        try:
            base = sharding.load_contract(base_path)
            cur = sharding.load_contract(cur_path)
        except (OSError, ValueError, KeyError) as e:
            print("mxlint: cannot read collective contract: %s" % e,
                  file=sys.stderr)
            return 2
        diags.extend(d for d in sharding.diff_contract(base, cur)
                     if d.rule not in ignore)

    if args.perf_diff:
        base_path, cur_path = args.perf_diff
        try:
            base = perf.load_audit(base_path)
            cur = perf.load_audit(cur_path)
        except (OSError, ValueError, KeyError) as e:
            print("mxlint: cannot read perf audit: %s" % e,
                  file=sys.stderr)
            return 2
        diags.extend(d for d in perf.diff_audit(base, cur)
                     if d.rule not in ignore)

    if args.numerics_diff:
        base_path, cur_path = args.numerics_diff
        try:
            base = numerics.load_audit(base_path)
            cur = numerics.load_audit(cur_path)
        except (OSError, ValueError, KeyError) as e:
            print("mxlint: cannot read numerics audit: %s" % e,
                  file=sys.stderr)
            return 2
        diags.extend(d for d in numerics.diff_audit(base, cur)
                     if d.rule not in ignore)

    if args.memory_diff:
        base_path, cur_path = args.memory_diff
        try:
            base = memory.load_audit(base_path)
            cur = memory.load_audit(cur_path)
        except (OSError, ValueError, KeyError) as e:
            print("mxlint: cannot read memory audit: %s" % e,
                  file=sys.stderr)
            return 2
        diags.extend(d for d in memory.diff_audit(base, cur)
                     if d.rule not in ignore)

    if not paths and not args.graph and not run_retrace \
            and not args.changed and not args.collective_diff \
            and not args.perf_diff and not args.numerics_diff \
            and not args.memory_diff:
        _build_parser().print_usage()
        return 2

    if args.baseline:
        try:
            known = _load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print("mxlint: cannot read baseline %s: %s"
                  % (args.baseline, e), file=sys.stderr)
            return 2
        diags = [d for d in diags if _baseline_key(d) not in known]

    if args.write_baseline:
        _write_baseline(args.write_baseline, diags)
        print("mxlint: wrote %d finding(s) to baseline %s"
              % (len(diags), args.write_baseline))
        return 0

    if args.sarif:
        from .sarif import write_sarif
        write_sarif(args.sarif, diags)

    print(render_json(diags) if args.as_json else render_human(diags))
    failing = [d for d in diags
               if d.severity == ERROR or args.strict]
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
