"""Sharding sanitizer (ISSUE 7 tentpole): SPMD spec linter, donation
auditor, and compiled-collective contracts.

ROADMAP item 3 collapses TrainStep/Trainer/KVStore onto ONE
GSPMD-compiled program over a mesh.  That refactor lives or dies on
sharding discipline: a `PartitionSpec` naming a mesh axis that doesn't
exist silently replicates, a missing ``donate_argnums`` doubles peak
HBM on every step, and one mismatched spec becomes a GSPMD all-gather
that eats the MFU budget item 2 is chasing.  Nothing machine-checked
any of this; this pass does, in two layers:

**Static layer** (AST, under the PR-1 rule framework; runs in
``mxlint --self``):

- ``mesh-axis-unknown`` (project-wide): a ``PartitionSpec``/``P`` names
  an axis no ``Mesh``/``make_mesh`` call in the linted tree declares
  and that is not in the canonical ``parallel.mesh.AXIS_ROLES``
  vocabulary.  Axis names reaching ``P(...)`` through variables are
  resolved best-effort (string literals, parameter defaults,
  ``self._axis``-style attributes bound in ``__init__``).
- ``shard-map-spec-arity``: ``shard_map`` ``in_specs``/``out_specs``
  tuple arity vs the body's signature/returns (covers the
  ``parallel._shard_map`` compat wrapper and ``functools.partial``
  bodies).
- ``undonated-train-state``: a ``jax.jit`` of a train-step-shaped
  function (name contains train/step, or positional params carry
  param/optimizer-state names) without ``donate_argnums`` -- each
  dispatch keeps input AND output state buffers live, doubling peak
  HBM.  ``jit_kwargs["donate_argnums"] = ...`` + ``jax.jit(fn,
  **jit_kwargs)`` (the ``parallel.data_parallel`` idiom) counts as
  donated.
- ``donated-reuse``: an array passed at a donated position is read
  again after the jit call -- donation invalidated the buffer.
- ``implicit-reshard``: ``jax.device_put`` onto a ``NamedSharding``
  inside a ``for``/``while`` loop with no sharding-equivalence guard
  -- a committed array resharded per iteration is hidden per-step
  collective traffic.

**Compiled layer** (reuses PR 6's HLO category parser): every
executable the profiling capture surface registered is lowered (hits
jax's executable cache) and its collective instructions extracted into
a per-executable ``{kind: {count, bytes}}`` contract.
``save_contract``/``diff_contract`` + the committed
``ci/sharding_baseline.json`` make CI fail -- naming the executable
and the collective kind -- the moment GSPMD starts inserting
resharding all-gathers the baseline doesn't bless (rule
``collective-drift``, CLI ``mxlint --collective-diff``).  Arm capture
without full profiling via ``MXNET_TPU_SHARD_CHECK=1``.

``transfer_guard``/``MXNET_TPU_TRANSFER_GUARD`` wire
``jax.transfer_guard`` so a silent host transfer inside the step
(a Python scalar leaking into dispatch) raises instead of stalling the
pipeline (docs/sharding.md).
"""
from __future__ import annotations

import ast
import json
import os
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Diagnostic, WARNING, filter_suppressed, rule

__all__ = [
    "audit_sharding", "declared_axes",
    "collective_profile", "collective_contract", "save_contract",
    "load_contract", "diff_contract", "CONTRACT_SCHEMA",
    "transfer_guard", "install_transfer_guard", "shard_check_enabled",
]

# constructors that build a partition spec / mesh, by their usual names
_P_FUNCS = {"P", "PartitionSpec"}
_MESH_FUNCS = {"Mesh", "make_mesh"}
_SHARD_MAP_FUNCS = {"shard_map", "_shard_map"}
# module-level assignment targets that declare an axis vocabulary
_AXIS_DECL_RE = re.compile(r"(AXIS|AXES)")
# function names that read as a compiled train step
_STEP_NAME_RE = re.compile(r"(train|step)", re.I)
# positional parameter names that carry param/optimizer-state buffers
_STATE_PARAMS = {"pvals", "svals", "params", "param_vals", "state",
                 "states", "opt_state", "weights", "diff", "nondiff",
                 "train_state", "grads"}


def _call_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_str_const(node) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, str)


def _param_str_defaults(fn) -> Dict[str, str]:
    """Parameter name -> string-literal default of one function def."""
    out = {}
    a = fn.args
    pos = list(a.posonlyargs) + list(a.args)
    for arg, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        if _is_str_const(d):
            out[arg.arg] = d.value
    for arg, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None and _is_str_const(d):
            out[arg.arg] = d.value
    return out


class _StrEnv:
    """Best-effort map from names/``self.X`` attributes to the string
    literals they are bound to, for resolving axis names that reach a
    ``PartitionSpec`` through a variable."""

    def __init__(self, tree):
        self.module: Dict[str, str] = {}
        self.cls_attrs: Dict[str, Dict[str, str]] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and _is_str_const(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module[t.id] = node.value.value
            elif isinstance(node, ast.ClassDef):
                self.cls_attrs[node.name] = self._attr_strings(node)

    @staticmethod
    def _attr_strings(cls) -> Dict[str, str]:
        out = {}
        for meth in ast.walk(cls):
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            defaults = _param_str_defaults(meth)
            for node in ast.walk(meth):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                v = node.value
                if _is_str_const(v):
                    out[t.attr] = v.value
                elif isinstance(v, ast.Name) and v.id in defaults:
                    out[t.attr] = defaults[v.id]
        return out

    def resolve(self, expr, scopes: List[Dict[str, str]],
                cls: Optional[str]) -> Optional[str]:
        """The string ``expr`` denotes, or None when not resolvable."""
        if _is_str_const(expr):
            return expr.value
        if isinstance(expr, ast.Name):
            for env in reversed(scopes):
                if expr.id in env:
                    return env[expr.id]
            return self.module.get(expr.id)
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls:
            return self.cls_attrs.get(cls, {}).get(expr.attr)
        return None


def _local_str_env(fn) -> Dict[str, str]:
    """Parameter defaults + simple string assignments of one scope."""
    env = _param_str_defaults(fn) if not isinstance(fn, ast.Lambda) else {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_str_const(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    env[t.id] = node.value.value
    return env


# ----------------------------------------------------------------------
# mesh-axis-unknown (project-wide: declarations span files)
# ----------------------------------------------------------------------

def _parse_tree(paths) -> Iterable[Tuple[str, ast.AST, List[str]]]:
    for path in paths:
        p = Path(path)
        if not p.exists():
            continue
        files = sorted(p.glob("**/*.py")) if p.is_dir() else [p]
        for f in files:
            try:
                src = f.read_text()
                yield str(f), ast.parse(src, str(f)), src.splitlines()
            except (OSError, SyntaxError):
                continue


def _axes_of_tree(tree) -> Set[str]:
    axes: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name == "make_mesh" and node.args \
                    and isinstance(node.args[0], ast.Dict):
                axes.update(k.value for k in node.args[0].keys
                            if _is_str_const(k))
            elif name == "Mesh":
                cand = node.args[1] if len(node.args) >= 2 else None
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        cand = kw.value
                if isinstance(cand, (ast.Tuple, ast.List)):
                    axes.update(e.value for e in cand.elts
                                if _is_str_const(e))
                elif _is_str_const(cand):
                    axes.add(cand.value)
        elif isinstance(node, ast.Assign):
            # `AXIS_ROLES = {...}` / `KNOWN_AXES = (...)` declarations
            named = any(isinstance(t, ast.Name)
                        and _AXIS_DECL_RE.search(t.id)
                        for t in node.targets)
            if not named:
                continue
            v = node.value
            if isinstance(v, ast.Dict):
                axes.update(k.value for k in v.keys if _is_str_const(k))
            elif isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                axes.update(e.value for e in v.elts if _is_str_const(e))
    return axes


def declared_axes(paths) -> Set[str]:
    """Mesh axes the linted tree declares: ``make_mesh({...})`` dict
    keys, ``Mesh(..., (...))`` axis-name tuples, and module-level
    ``*_AXES``/``AXIS_ROLES`` vocabularies."""
    axes: Set[str] = set()
    for _path, tree, _src in _parse_tree(paths):
        axes.update(_axes_of_tree(tree))
    return axes


def _canonical_axes() -> Set[str]:
    """The framework's own axis vocabulary (``parallel.mesh``), so a
    single-file lint doesn't flag the conventional roles the package
    declares elsewhere."""
    try:
        from ..parallel.mesh import AXIS_ROLES
        return set(AXIS_ROLES)
    except Exception:
        return set()


class _SpecAxisVisitor(ast.NodeVisitor):
    """Collects axis-name strings used inside ``P``/``PartitionSpec``
    calls, resolved through the string environment."""

    def __init__(self, tree, path):
        self.path = path
        self.env = _StrEnv(tree)
        self.cls: Optional[str] = None
        self.scopes: List[Dict[str, str]] = []
        self.uses: List[Tuple[str, int]] = []     # (axis, lineno)

    def visit_ClassDef(self, node):
        prev, self.cls = self.cls, node.name
        self.generic_visit(node)
        self.cls = prev

    def visit_FunctionDef(self, node):
        self.scopes.append(_local_str_env(node))
        self.generic_visit(node)
        self.scopes.pop()

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Call(self, node):
        if _call_name(node) in _P_FUNCS:
            for arg in node.args:
                elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) \
                    else [arg]
                for e in elts:
                    if isinstance(e, ast.Starred):
                        continue
                    axis = self.env.resolve(e, self.scopes, self.cls)
                    if axis is not None:
                        self.uses.append((axis, e.lineno))
        self.generic_visit(node)


def audit_sharding(paths, ignore=(), report_files=None
                   ) -> List[Diagnostic]:
    """Project half of the pass: gather declared mesh axes over the
    whole linted tree, then flag every ``PartitionSpec`` axis outside
    that vocabulary.  ``report_files`` restricts *reporting* -- not the
    declaration scan -- for ``--changed`` runs (same contract as
    ``concurrency.audit_lock_order``)."""
    if "mesh-axis-unknown" in ignore:
        return []
    trees = list(_parse_tree(paths))
    known = _canonical_axes()
    for _path, tree, _src in trees:
        known.update(_axes_of_tree(tree))
    diags: List[Diagnostic] = []
    for path, tree, src_lines in trees:
        if report_files is not None and path not in report_files:
            continue
        v = _SpecAxisVisitor(tree, path)
        v.visit(tree)
        file_diags = []
        for axis, line in v.uses:
            if axis in known:
                continue
            hint = ""
            if known:
                import difflib
                close = difflib.get_close_matches(axis, sorted(known), 1)
                if close:
                    hint = "; did you mean %r?" % close[0]
            file_diags.append(Diagnostic(
                "mesh-axis-unknown",
                "PartitionSpec names mesh axis %r but no Mesh/"
                "make_mesh in the linted tree declares it (known: "
                "%s)%s -- an unknown axis silently replicates instead "
                "of sharding" % (axis, ", ".join(sorted(known)) or
                                 "<none>", hint),
                file=path, line=line))
        diags.extend(filter_suppressed(file_diags, src_lines))
    return diags


@rule("mesh-axis-unknown", "project",
      "A PartitionSpec names a mesh axis no Mesh/make_mesh call in the "
      "linted tree declares (and that is outside parallel.mesh."
      "AXIS_ROLES); XLA treats an unknown axis as replicated -- the "
      "shard silently never happens.")
def _rule_mesh_axis(paths, ctx):
    return audit_sharding(paths)


# ----------------------------------------------------------------------
# shard-map-spec-arity (per-file)
# ----------------------------------------------------------------------

def _positional_params(fn) -> Tuple[List[str], bool]:
    a = fn.args
    names = [p.arg for p in list(a.posonlyargs) + list(a.args)]
    if names and names[0] == "self":
        names = names[1:]
    return names, a.vararg is not None


def _file_defs_and_assigns(tree):
    defs = {}
    assigns = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            assigns[node.targets[0].id] = node.value
    return defs, assigns


def _resolve_body(expr, defs, assigns, depth=0):
    """``(positional_param_names, has_vararg, fn_node_or_None)`` of a
    shard_map body expression, following names and functools.partial."""
    if depth > 4 or expr is None:
        return None
    if isinstance(expr, ast.Lambda):
        names, vararg = _positional_params(expr)
        return names, vararg, None
    if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef)):
        names, vararg = _positional_params(expr)
        return names, vararg, expr
    if isinstance(expr, ast.Name):
        if expr.id in defs:
            return _resolve_body(defs[expr.id], defs, assigns, depth + 1)
        if expr.id in assigns:
            return _resolve_body(assigns[expr.id], defs, assigns,
                                 depth + 1)
        return None
    if isinstance(expr, ast.Call) and _call_name(expr) == "partial" \
            and expr.args:
        inner = _resolve_body(expr.args[0], defs, assigns, depth + 1)
        if inner is None:
            return None
        names, vararg, fn_node = inner
        consumed = len(expr.args) - 1
        kwnames = {kw.arg for kw in expr.keywords if kw.arg}
        remaining = [n for n in names[consumed:] if n not in kwnames]
        return remaining, vararg, fn_node
    return None


def _own_returns(fn) -> List[ast.expr]:
    """Return expressions at the body function's own level (nested defs
    excluded -- their returns belong to another computation)."""
    out = []
    stack = list(fn.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.Return) and n.value is not None:
            out.append(n.value)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _spec_arity(expr) -> Optional[int]:
    """Arity of a specs argument: only literal tuples/lists count (a
    single spec is a pytree prefix broadcast over every arg)."""
    if isinstance(expr, (ast.Tuple, ast.List)):
        if any(isinstance(e, ast.Starred) for e in expr.elts):
            return None
        return len(expr.elts)
    return None


@rule("shard-map-spec-arity", "ast",
      "shard_map in_specs/out_specs tuple arity disagrees with the "
      "body's positional signature / returned tuple (including the "
      "parallel._shard_map compat wrapper and functools.partial "
      "bodies); jax raises a cryptic tree-mismatch at trace time.")
def _lint_shard_map_arity(tree, path, ctx):
    defs, assigns = _file_defs_and_assigns(tree)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _call_name(node) in _SHARD_MAP_FUNCS and node.args):
            continue
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        in_specs = kwargs.get(
            "in_specs", node.args[2] if len(node.args) > 2 else None)
        out_specs = kwargs.get(
            "out_specs", node.args[3] if len(node.args) > 3 else None)
        body = _resolve_body(node.args[0], defs, assigns)
        if body is None:
            continue
        names, vararg, fn_node = body
        n_in = _spec_arity(in_specs)
        if n_in is not None and not vararg and n_in != len(names):
            yield Diagnostic(
                "shard-map-spec-arity",
                "shard_map body takes %d positional arg(s) %s but "
                "in_specs has %d spec(s)" % (len(names), names, n_in),
                file=path, line=node.lineno)
        n_out = _spec_arity(out_specs)
        if n_out is not None and fn_node is not None:
            rets = _own_returns(fn_node)
            if rets and all(isinstance(r, ast.Tuple) for r in rets):
                lens = {len(r.elts) for r in rets}
                if len(lens) == 1 and lens != {n_out}:
                    yield Diagnostic(
                        "shard-map-spec-arity",
                        "shard_map body returns a %d-tuple but "
                        "out_specs has %d spec(s)"
                        % (lens.pop(), n_out),
                        file=path, line=node.lineno)


# ----------------------------------------------------------------------
# undonated-train-state (per-file)
# ----------------------------------------------------------------------

def _is_jit_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit" \
            and isinstance(f.value, ast.Name) and f.value.id == "jax":
        return True
    return isinstance(f, ast.Name) and f.id == "jit"


def _has_donation(call: ast.Call, enclosing_fn) -> bool:
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            return True
        if kw.arg is None and isinstance(kw.value, ast.Name) \
                and enclosing_fn is not None:
            # jax.jit(fn, **jit_kwargs) with a conditional
            # jit_kwargs["donate_argnums"] = ... in the enclosing scope
            # (the parallel.data_parallel idiom) counts as donated
            target = kw.value.id
            for n in ast.walk(enclosing_fn):
                if not isinstance(n, ast.Assign):
                    continue
                for t in n.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == target \
                            and _is_str_const(t.slice) \
                            and t.slice.value in ("donate_argnums",
                                                  "donate_argnames"):
                        return True
    return False


@rule("undonated-train-state", "ast",
      "A jax.jit of a train-step-shaped function (name contains "
      "train/step, or positional params carry param/optimizer-state "
      "names) without donate_argnums: every dispatch keeps input AND "
      "output state buffers live, doubling peak HBM.  Donate the state "
      "argnums, or suppress with the reason the buffers must survive.")
def _lint_undonated_train_state(tree, path, ctx):
    defs, assigns = _file_defs_and_assigns(tree)

    class V(ast.NodeVisitor):
        def __init__(self):
            self.fn = None
            self.hits = []

        def visit_FunctionDef(self, node):
            prev, self.fn = self.fn, node
            self.generic_visit(node)
            self.fn = prev

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if _is_jit_call(node) and node.args:
                self.hits.append((node, self.fn))
            self.generic_visit(node)

    v = V()
    v.visit(tree)
    for call, enclosing in v.hits:
        body = _resolve_body(call.args[0], defs, assigns)
        if body is None:
            continue
        names, _vararg, fn_node = body
        fn_name = fn_node.name if fn_node is not None else ""
        stateish = sorted(set(names) & _STATE_PARAMS)
        if not (_STEP_NAME_RE.search(fn_name) or stateish):
            continue
        if _has_donation(call, enclosing):
            continue
        why = ("is named %r" % fn_name) if _STEP_NAME_RE.search(fn_name) \
            else ("takes state buffers %s" % stateish)
        yield Diagnostic(
            "undonated-train-state",
            "jax.jit of a step function that %s has no donate_argnums; "
            "the un-donated input state stays live across the dispatch "
            "(2x peak HBM for params+optimizer state).  Donate the "
            "state argnums or suppress with the reason the buffers "
            "must outlive the call" % why,
            file=path, line=call.lineno)


# ----------------------------------------------------------------------
# donated-reuse (per-file, same-scope)
# ----------------------------------------------------------------------

def _donated_positions(call: ast.Call) -> Optional[List[int]]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return [v.value]
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if not (isinstance(e, ast.Constant)
                            and isinstance(e.value, int)):
                        return None
                    out.append(e.value)
                return out
    return None


@rule("donated-reuse", "ast",
      "An array passed at a donated argnum is read again after the "
      "donating jit call; donation hands the buffer to XLA and the "
      "later read sees a deleted array (jax raises on some backends, "
      "silently aliases on others).  Use the returned array.")
def _lint_donated_reuse(tree, path, ctx):
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        body = scope.body if isinstance(scope, ast.Module) else scope.body
        # donating jits assigned to a name in THIS scope
        donated_fns = {}           # name -> positions
        for node in body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _is_jit_call(node.value) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                pos = _donated_positions(node.value)
                if pos:
                    donated_fns[node.targets[0].id] = pos
        if not donated_fns:
            continue
        # name events in statement order (nested defs excluded: they run
        # on their own schedule)
        events = []                # (lineno, name, is_store)
        calls = []                 # (lineno, [donated arg names])
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in donated_fns:
                donated = []
                for i in donated_fns[n.func.id]:
                    if i < len(n.args) and isinstance(n.args[i],
                                                      ast.Name):
                        donated.append(n.args[i].id)
                if donated:
                    calls.append((n.lineno, donated))
            if isinstance(n, ast.Name):
                events.append((n.lineno, n.id,
                               isinstance(n.ctx, ast.Store)))
            stack.extend(ast.iter_child_nodes(n))
        for call_line, names in calls:
            for name in names:
                stores_after = [ln for ln, nm, st in events
                                if nm == name and st and ln >= call_line]
                for ln, nm, st in sorted(events):
                    if nm != name or st or ln <= call_line:
                        continue
                    if any(s <= ln for s in stores_after):
                        break      # rebound before this read
                    yield Diagnostic(
                        "donated-reuse",
                        "%r was donated to the jit call on line %d and "
                        "is read again here; the buffer no longer "
                        "exists -- use the jit call's returned array"
                        % (name, call_line),
                        file=path, line=ln)
                    break          # one diagnostic per donated name


# ----------------------------------------------------------------------
# implicit-reshard (per-file)
# ----------------------------------------------------------------------

def _sharding_ish(expr, sharded_names: Set[str]) -> bool:
    """Heuristic: the expression denotes a NamedSharding."""
    if isinstance(expr, ast.Call):
        name = _call_name(expr) or ""
        return name == "NamedSharding" or "sharding" in name.lower()
    if isinstance(expr, ast.Name):
        return expr.id in sharded_names
    if isinstance(expr, ast.Attribute):
        return "sharding" in expr.attr.lower()
    return False


@rule("implicit-reshard", "ast",
      "jax.device_put onto a NamedSharding inside a for/while loop "
      "with no sharding-equivalence guard: an already-committed array "
      "resharded every iteration is hidden per-step collective/"
      "transfer traffic.  Place once outside the loop, or guard with "
      "`if not x.sharding.is_equivalent_to(want, ndim)`.")
def _lint_implicit_reshard(tree, path, ctx):
    class V(ast.NodeVisitor):
        def __init__(self):
            self.loops = 0
            self.guards = 0
            self.sharded_names: List[Set[str]] = [set()]
            self.hits = []

        def visit_FunctionDef(self, node):
            prev_loops, self.loops = self.loops, 0
            prev_guards, self.guards = self.guards, 0
            self.sharded_names.append(set())
            self.generic_visit(node)
            self.sharded_names.pop()
            self.loops, self.guards = prev_loops, prev_guards

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Assign(self, node):
            if isinstance(node.value, ast.Call) \
                    and _sharding_ish(node.value, set()):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.sharded_names[-1].add(t.id)
            self.generic_visit(node)

        def _loop(self, node):
            self.loops += 1
            self.generic_visit(node)
            self.loops -= 1

        visit_For = _loop
        visit_While = _loop
        visit_AsyncFor = _loop

        def visit_If(self, node):
            guarded = any(
                isinstance(n, ast.Attribute)
                and n.attr in ("is_equivalent_to", "sharding")
                for n in ast.walk(node.test))
            self.guards += 1 if guarded else 0
            self.generic_visit(node)
            self.guards -= 1 if guarded else 0

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "device_put" \
                    and self.loops and not self.guards \
                    and len(node.args) >= 2:
                names = set()
                for s in self.sharded_names:
                    names |= s
                if _sharding_ish(node.args[1], names):
                    self.hits.append(node)
            self.generic_visit(node)

    v = V()
    v.visit(tree)
    for node in v.hits:
        yield Diagnostic(
            "implicit-reshard",
            "device_put onto a NamedSharding inside a loop: a "
            "committed array is resharded every iteration (hidden "
            "collective/transfer per step).  Hoist the placement out "
            "of the loop or guard with sharding.is_equivalent_to",
            file=path, line=node.lineno)


# ----------------------------------------------------------------------
# Compiled layer: collective contracts over registered executables
# ----------------------------------------------------------------------

CONTRACT_SCHEMA = "mxshard.collectives.v1"


def shard_check_enabled() -> bool:
    """Whether ``MXNET_TPU_SHARD_CHECK=1`` armed executable capture for
    the collective auditor (rides the ``mx.profiling`` capture
    surface; see docs/sharding.md)."""
    return os.environ.get("MXNET_TPU_SHARD_CHECK", "0") != "0"


def collective_profile(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """Per-kind collective op counts/bytes of one compiled module:
    ``{"all-reduce": {"count": 2, "bytes": 4096}, ...}``.

    Reuses the PR-6 HLO parser; async pairs count once (the ``-start``
    carries the cost, the ``-done`` is bookkeeping), ``partition-id``/
    ``replica-id`` are metadata reads, not traffic.  Bytes are the
    instruction's output bytes -- the payload the ICI/DCN link moves.
    """
    from ..profiling import hlo
    _entry, comps, _refs = hlo.parse_module(hlo_text)
    kinds: Dict[str, Dict[str, int]] = {}
    for _name, instrs in comps.items():
        for ins in instrs:
            if hlo.category_of(ins) != "collective":
                continue
            op = ins.opcode
            if op in ("partition-id", "replica-id") \
                    or op.endswith("-done"):
                continue
            kind = op[:-len("-start")] if op.endswith("-start") else op
            if op == "custom-call":
                tm = hlo._CUSTOM_TARGET_RE.search(ins.attrs)
                kind = "custom:%s" % (tm.group(1) if tm else "unknown")
            rec = kinds.setdefault(kind, {"count": 0, "bytes": 0})
            rec["count"] += 1
            rec["bytes"] += hlo._nbytes(ins.out_shapes)
    return kinds


def collective_contract() -> dict:
    """The current process's collective contract: every executable the
    profiling/shard-check capture surface registered, lowered (hits
    jax's executable cache) and profiled for collectives.  Executables
    with zero collectives are omitted -- ``diff_contract`` treats a
    missing entry as zero, so a label that GAINS collectives is flagged
    even when the baseline never listed it."""
    import jax
    from ..profiling import store
    execs: Dict[str, Dict[str, Dict[str, int]]] = {}
    for label, compiled in store.compiled_executables():
        try:
            text = compiled.as_text()
        except Exception:
            continue
        prof = collective_profile(text)
        if not prof:
            continue
        agg = execs.setdefault(label, {})
        for kind, rec in prof.items():
            cur = agg.setdefault(kind, {"count": 0, "bytes": 0})
            cur["count"] += rec["count"]
            cur["bytes"] += rec["bytes"]
    try:
        backend = jax.default_backend()
        n_dev = len(jax.devices())
    except Exception:
        backend, n_dev = "unknown", 0
    return {"schema": CONTRACT_SCHEMA, "backend": backend,
            "n_devices": n_dev, "executables": execs}


def save_contract(path: str) -> dict:
    """Write the current collective contract as JSON (the artifact CI
    diffs against the committed ``ci/sharding_baseline.json``)."""
    c = collective_contract()
    with open(path, "w") as f:
        json.dump(c, f, indent=1, sort_keys=True)
        f.write("\n")
    return c


def load_contract(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if data.get("schema") != CONTRACT_SCHEMA:
        raise ValueError("%s is not a %s artifact (schema=%r)"
                         % (path, CONTRACT_SCHEMA, data.get("schema")))
    return data


def diff_contract(baseline: dict, current: dict,
                  bytes_tol: float = 0.5) -> List[Diagnostic]:
    """Collective drift of ``current`` vs the blessed ``baseline``:

    - a collective kind the baseline doesn't bless for that executable
      (or a brand-new executable with collectives) -> error;
    - a blessed kind whose count GREW -> error;
    - a blessed kind whose bytes grew past ``bytes_tol`` -> warning.

    Fewer/smaller collectives than blessed pass silently (an
    improvement is not drift); re-bless with ``save_contract`` after
    an intentional change."""
    diags: List[Diagnostic] = []
    base_ex = baseline.get("executables", {})
    for label, kinds in sorted(current.get("executables", {}).items()):
        blessed = base_ex.get(label, {})
        for kind, rec in sorted(kinds.items()):
            b = blessed.get(kind)
            if b is None:
                diags.append(Diagnostic(
                    "collective-drift",
                    "executable %r gained %d unblessed %r "
                    "collective(s) (%d bytes): GSPMD is inserting "
                    "resharding traffic the baseline does not bless -- "
                    "fix the PartitionSpec (or re-bless via "
                    "analysis.sharding.save_contract)"
                    % (label, rec["count"], kind, rec["bytes"]),
                    node=label))
            elif rec["count"] > b["count"]:
                diags.append(Diagnostic(
                    "collective-drift",
                    "executable %r: %r collectives grew %d -> %d; the "
                    "compiled step is moving more data over the "
                    "interconnect than the baseline blesses"
                    % (label, kind, b["count"], rec["count"]),
                    node=label))
            elif b.get("bytes", 0) > 0 and \
                    rec["bytes"] > b["bytes"] * (1.0 + bytes_tol):
                diags.append(Diagnostic(
                    "collective-drift",
                    "executable %r: %r collective bytes grew %d -> %d "
                    "(> %d%% tolerance)"
                    % (label, kind, b["bytes"], rec["bytes"],
                       int(bytes_tol * 100)),
                    node=label, severity=WARNING))
    return diags


@rule("collective-drift", "compiled",
      "A registered executable's GSPMD-inserted collectives (kind/"
      "count/bytes per executable) drifted past the committed "
      "ci/sharding_baseline.json -- a mismatched PartitionSpec became "
      "a resharding all-gather.  Gate: mxlint --collective-diff.")
def _rule_collective_drift(baseline, current):
    return diff_contract(baseline, current)


# ----------------------------------------------------------------------
# Transfer guard
# ----------------------------------------------------------------------

_GUARD_MODES = ("allow", "log", "disallow", "log_explicit",
                "disallow_explicit")


def transfer_guard(mode="disallow"):
    """Scoped ``jax.transfer_guard``: inside the context, implicit
    host<->device transfers (a Python scalar leaking into dispatch, an
    un-placed index array) raise under ``"disallow"`` instead of
    silently stalling the step.  Explicit ``device_put``/staging is
    always allowed under ``"disallow"`` -- the feed pipeline keeps
    working; use ``"disallow_explicit"`` to forbid those too."""
    import jax
    return jax.transfer_guard(mode)


def install_transfer_guard(mode=None):
    """Apply the process-global transfer guard (called at package
    import when ``MXNET_TPU_TRANSFER_GUARD`` is set).  Returns the
    installed mode or None."""
    mode = mode if mode is not None else \
        os.environ.get("MXNET_TPU_TRANSFER_GUARD", "")
    if not mode:
        return None
    if mode not in _GUARD_MODES:
        from ..base import MXNetError
        raise MXNetError(
            "MXNET_TPU_TRANSFER_GUARD=%r is not one of %s"
            % (mode, ", ".join(_GUARD_MODES)))
    import jax
    jax.config.update("jax_transfer_guard", mode)
    return mode
