"""SARIF 2.1.0 export of mxlint findings (ISSUE 16 satellite).

``mxlint --sarif OUT.sarif`` serializes EVERY surviving diagnostic --
all passes, not just numerics -- as one SARIF run, so CI systems that
speak the OASIS Static Analysis Results Interchange Format (GitHub code
scanning, Azure DevOps, VS Code SARIF viewer) surface mxlint findings
as inline annotations.  The CLI's exit-code contract is unchanged: the
export is a side artifact, not a reporting mode.

Only the schema's *required* fields are emitted (version, runs,
tool.driver.name, result ruleId/level/message), plus the optional
fields CI annotators actually consume: rule metadata
(shortDescription/fullDescription from the registry docstrings) and
physical locations (artifactLocation.uri + region.startLine).
"""
from __future__ import annotations

import json
from typing import Dict, List

from .core import ERROR, RULES, Diagnostic

__all__ = ["SARIF_VERSION", "SARIF_SCHEMA", "to_sarif", "write_sarif"]

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _rule_meta(rule_id: str) -> Dict:
    meta = {"id": rule_id}
    reg = RULES.get(rule_id)
    if reg is not None and reg.doc:
        first = reg.doc.split(". ")[0].rstrip(".") + "."
        meta["shortDescription"] = {"text": first}
        meta["fullDescription"] = {"text": reg.doc}
    else:
        # ad-hoc diagnostics (syntax-error, graph-load) carry no
        # registry entry; SARIF still requires the id
        meta["shortDescription"] = {"text": rule_id}
    return meta


def _result(d: Diagnostic) -> Dict:
    res = {
        "ruleId": d.rule,
        "level": "error" if d.severity == ERROR else "warning",
        "message": {"text": d.message},
    }
    if d.file:
        region = {}
        if d.line:
            region["startLine"] = int(d.line)
        loc = {"artifactLocation": {"uri": d.file}}
        if region:
            loc["region"] = region
        res["locations"] = [{"physicalLocation": loc}]
    return res


def to_sarif(diags: List[Diagnostic]) -> Dict:
    """The findings as one SARIF 2.1.0 log object (a single run,
    driver ``mxlint``); rule metadata is pulled from the registry for
    every rule id present."""
    seen, rules = set(), []
    for d in diags:
        if d.rule not in seen:
            seen.add(d.rule)
            rules.append(_rule_meta(d.rule))
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "mxlint",
                "informationUri":
                    "https://github.com/apache/incubator-mxnet",
                "rules": rules,
            }},
            "results": [_result(d) for d in diags],
        }],
    }


def write_sarif(path: str, diags: List[Diagnostic]) -> Dict:
    log = to_sarif(diags)
    with open(path, "w") as f:
        json.dump(log, f, indent=1, sort_keys=True)
        f.write("\n")
    return log
