"""Trace-safety AST linter for compiled (hybridized/jitted) paths.

The JAX lowering adds a failure class the reference never had: Python
that is fine eagerly but breaks (or silently de-optimizes) under
``jax.jit`` tracing.  A ``hybrid_forward`` body is traced by the
CachedOp engine (``gluon/block.py``), so inside it:

- host syncs (``.asnumpy()``, ``float(x)``, ``np.asarray(x)``) raise a
  ``TracerArrayConversionError`` at trace time;
- Python ``if``/``while`` on a traced *value* raises a
  ``TracerBoolConversionError`` (branching on ``is None`` /
  ``isinstance`` / shapes is structural and fine -- shapes are static
  under jit);

and everywhere in library code:

- mutable default arguments alias state across calls;
- bare ``except:`` swallows ``KeyboardInterrupt``/preemption SIGTERM
  handling (migrated from the old inline CI check).

Suppress a finding with ``# mxlint: disable=<rule>`` on its line.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, List

from .core import Diagnostic, filter_suppressed, rule

__all__ = ["lint_source", "lint_file", "lint_paths", "TRACED_SCOPES"]

# Method names whose bodies run under the tracer.  ``hybrid_forward`` is
# the public contract; ``_forward_impl`` is the engine-internal twin the
# cache actually traces (HybridSequential overrides it directly).
TRACED_SCOPES = ("hybrid_forward", "_forward_impl")

# attribute reads that touch only static metadata of a traced value
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "context", "name"}
# calls that inspect structure, not value
_STATIC_CALLS = {"isinstance", "len", "hasattr", "type", "getattr",
                 "enumerate", "zip", "range", "list", "tuple", "id"}
# method calls that force a device->host transfer of a traced value
_SYNC_METHODS = {"asnumpy", "asscalar", "item", "tolist", "wait_to_read"}
# builtins that coerce a traced value to a Python scalar
_COERCIONS = {"float", "int", "bool", "complex"}
# numpy module aliases whose array constructors pull values to host
_NP_MODULES = {"np", "numpy", "onp"}
_NP_SYNC_FUNCS = {"asarray", "array", "asanyarray", "ascontiguousarray"}


def _traced_value_uses(expr, traced) -> List[ast.Name]:
    """Name nodes in ``expr`` that read a traced value's *data* (uses
    behind static metadata/structure accessors don't count)."""
    if expr is None:
        return []
    if isinstance(expr, ast.Name):
        return [expr] if expr.id in traced else []
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return []
        return _traced_value_uses(expr.value, traced)
    if isinstance(expr, ast.Call):
        f = expr.func
        fname = f.id if isinstance(f, ast.Name) else \
            (f.attr if isinstance(f, ast.Attribute) else None)
        if fname in _STATIC_CALLS:
            return []
        out = _traced_value_uses(f, traced)
        for a in expr.args:
            out += _traced_value_uses(a, traced)
        for k in expr.keywords:
            out += _traced_value_uses(k.value, traced)
        return out
    if isinstance(expr, ast.Compare):
        # identity checks (x is None / x is not y) are structural
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return []
    out = []
    for child in ast.iter_child_nodes(expr):
        out += _traced_value_uses(child, traced)
    return out


def _traced_names(fn: ast.FunctionDef) -> set:
    """Initial traced-value bindings of a traced scope: every tensor
    parameter (positional after self/F, kw-only, and **params)."""
    args = fn.args
    pos = [a.arg for a in args.posonlyargs + args.args]
    skip = 1 if pos and pos[0] == "self" else 0
    if fn.name == "hybrid_forward" and len(pos) > skip and \
            pos[skip] == "F":
        skip += 1
    names = set(pos[skip:])
    names.update(a.arg for a in args.kwonlyargs)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


class _TracedScopeVisitor(ast.NodeVisitor):
    """Walks one traced scope, propagating taint through assignments."""

    def __init__(self, fn: ast.FunctionDef):
        self.fn = fn
        self.traced = _traced_names(fn)
        self.host_syncs: List[Diagnostic] = []
        self.branches: List[Diagnostic] = []

    def run(self):
        for stmt in self.fn.body:
            self.visit(stmt)
        return self

    # taint propagation: a name assigned from an expression that reads a
    # traced value becomes traced itself
    def visit_Assign(self, node):
        self.generic_visit(node)
        if _traced_value_uses(node.value, self.traced):
            for tgt in node.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        self.traced.add(n.id)

    def visit_AugAssign(self, node):
        self.generic_visit(node)
        if _traced_value_uses(node.value, self.traced) and \
                isinstance(node.target, ast.Name):
            self.traced.add(node.target.id)

    def visit_FunctionDef(self, node):
        pass                          # nested defs get their own scope

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS and \
                _traced_value_uses(f.value, self.traced):
            self._sync(node, ".%s() forces a device->host sync" % f.attr)
        elif isinstance(f, ast.Name) and f.id in _COERCIONS and \
                any(_traced_value_uses(a, self.traced) for a in node.args):
            self._sync(node, "%s() coerces a traced value on host" % f.id)
        elif isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and \
                f.value.id in _NP_MODULES and f.attr in _NP_SYNC_FUNCS and \
                any(_traced_value_uses(a, self.traced) for a in node.args):
            self._sync(node, "%s.%s() materializes a traced value as a "
                       "host numpy array" % (f.value.id, f.attr))

    def _sync(self, node, what):
        self.host_syncs.append(Diagnostic(
            "host-sync",
            "%s inside %s; under hybridize/jit this raises at trace "
            "time -- keep the value on device (F./mx.nd ops) or compute "
            "it outside the compiled path" % (what, self.fn.name),
            line=node.lineno))

    def _branch(self, node, kw):
        uses = _traced_value_uses(node.test, self.traced)
        if uses:
            self.branches.append(Diagnostic(
                "tracer-branch",
                "`%s` on traced value(s) %s inside %s; data-dependent "
                "Python control flow breaks tracing -- use F.where/"
                "lax.cond-style select instead"
                % (kw, sorted({u.id for u in uses}), self.fn.name),
                line=node.lineno))

    def visit_If(self, node):
        self._branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._branch(node, "while")
        self.generic_visit(node)

    def visit_Assert(self, node):
        # assert on a traced value is a bool coercion too
        uses = _traced_value_uses(node.test, self.traced)
        if uses:
            self.branches.append(Diagnostic(
                "tracer-branch",
                "`assert` on traced value(s) %s inside %s; use "
                "explicit shape checks or F.where"
                % (sorted({u.id for u in uses}), self.fn.name),
                line=node.lineno))
        self.generic_visit(node)


def _traced_scopes(tree) -> Iterable[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name in TRACED_SCOPES:
            yield node


# ----------------------------------------------------------------------
# rules
# ----------------------------------------------------------------------

@rule("bare-except", "ast",
      "Bare `except:` catches KeyboardInterrupt and the preemption "
      "SIGTERM path; name the exception type (was the inline CI check).")
def _lint_bare_except(tree, path, ctx):
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield Diagnostic("bare-except",
                             "bare `except:`; catch a named exception "
                             "type", file=path, line=node.lineno)


@rule("mutable-default", "ast",
      "A mutable default argument (list/dict/set literal) is shared "
      "across every call of the function.")
def _lint_mutable_default(tree, path, ctx):
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = list(node.args.defaults) + \
            [d for d in node.args.kw_defaults if d is not None]
        for d in defaults:
            if isinstance(d, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
                yield Diagnostic(
                    "mutable-default",
                    "function %r has a mutable default argument; use "
                    "None and create it in the body" % node.name,
                    file=path, line=d.lineno)


@rule("host-sync", "ast",
      "A device->host transfer (.asnumpy()/.item()/float()/np.asarray) "
      "on a traced value inside a compiled scope fails at trace time.")
def _lint_host_sync(tree, path, ctx):
    for fn in _traced_scopes(tree):
        for d in _TracedScopeVisitor(fn).run().host_syncs:
            d.file = path
            yield d


@rule("tracer-branch", "ast",
      "Python if/while/assert on a traced value inside a compiled "
      "scope; data-dependent control flow breaks tracing.")
def _lint_tracer_branch(tree, path, ctx):
    for fn in _traced_scopes(tree):
        for d in _TracedScopeVisitor(fn).run().branches:
            d.file = path
            yield d


# ----------------------------------------------------------------------
# drivers
# ----------------------------------------------------------------------

def lint_source(source: str, path: str = "<string>",
                ignore=()) -> List[Diagnostic]:
    """Lint one source string; applies ``# mxlint: disable`` comments."""
    from .core import RULES
    try:
        tree = ast.parse(source, path)
    except SyntaxError as e:
        return [Diagnostic("syntax-error", str(e), file=path,
                           line=e.lineno or 1)]
    diags: List[Diagnostic] = []
    for r in RULES.values():
        if r.kind != "ast" or r.id in ignore:
            continue
        for d in r.check(tree, path, None):
            d.severity = r.severity
            diags.append(d)
    diags.sort(key=lambda d: (d.line or 0, d.rule))
    return filter_suppressed(diags, source.splitlines())


def lint_file(path, ignore=()) -> List[Diagnostic]:
    p = Path(path)
    return lint_source(p.read_text(), str(p), ignore=ignore)


def lint_paths(paths, ignore=()) -> List[Diagnostic]:
    """Lint files and/or directories (recursing into ``**/*.py``)."""
    diags: List[Diagnostic] = []
    for path in paths:
        p = Path(path)
        if not p.exists():
            diags.append(Diagnostic("no-such-path",
                                    "path does not exist", file=str(p)))
            continue
        files = sorted(p.glob("**/*.py")) if p.is_dir() else [p]
        for f in files:
            diags.extend(lint_file(f, ignore=ignore))
    return diags
