"""mxnumerics (ISSUE 16 tentpole): precision-flow sanitizer.

Every precision-critical surface -- bf16 training, AMP loss scaling,
fp32-sensitive reductions -- fails *silently*: a bf16 accumulation or an
unscaled half-precision loss trains fine for 10k steps and then diverges
with no attribution.  This pass guards all three layers, in the same
two-layer shape as the sharding sanitizer (PR 7) and perflint (PR 10),
plus a runtime sentinel:

**Static layer** (AST, under the PR-1 rule framework; runs in
``mxlint --self``):

- ``bf16-sensitive-reduce``: a sum/mean/var/std/norm/softmax reduction
  over a half-precision value inside a traced scope
  (``hybrid_forward``/``_forward_impl``/jitted step fns) with no
  explicit fp32 accumulation (``.astype(float32)`` upcast or
  ``preferred_element_type=``) -- the layernorm/softmax/BN-stats
  hazard: bf16 carries ~8 mantissa bits, so a long reduction loses
  everything below 1/256 of the running sum.
- ``unscaled-half-loss``: a half-precision loss fed to ``backward()``
  with no LossScaler / ``amp.scale_loss`` in the dataflow -- fp16
  gradients underflow to zero without scaling (bf16 shares fp32's
  exponent range; fp16 does not).
- ``half-optimizer-state``: optimizer state / EMA buffers created in
  fp16/bf16 -- momentum and variance accumulate tiny deltas that a
  half-precision store absorbs; state must be fp32 (the master-weights
  discipline).
- ``implicit-downcast``: an fp32 value or small Python-float constant
  silently narrowed by mixed-dtype promotion landing in half precision
  (a weak-typed scalar with a bf16 array stays bf16, so ``x + 1e-8``
  is ``x`` exactly in bf16).
- ``nonfinite-guard-missing``: ``log``/``rsqrt``/``reciprocal`` on an
  unbounded input with no eps/clip guard in the same expression --
  the first NaN factory every divergence postmortem finds.

**Compiled layer**: :func:`numerics_audit` walks PR 6's persistent
``profiling.store.compiled_executables()`` registry and audits the HLO
of each executable: dot/conv ops whose accumulator (output) type equals
a half-precision operand type (no fp32 accumulation), convert-op storms
(convert bytes >= 15% of executable bytes, with ``op_name``
provenance), and reductions computed entirely in bf16/f16.
``save_audit``/``load_audit``/``diff_audit`` (schema
``mxnumerics.audit.v1``) + the committed ``ci/numerics_baseline.json``
gate drift exactly like perflint: ``mxlint --numerics-diff BASE CUR``
errors on growth, passes on improvement (rule ``numerics-drift``;
CI stage ``numlint``; docs/numerics.md).

**Runtime layer**: the non-finite sentinel.  Behind
``MXNET_TPU_NUMERICS_CHECK=1`` (one module-flag check when off),
``TrainStep`` folds :func:`finite_tree` -- ONE fused in-graph
isfinite-reduction over the bucketed gradients
(``bucketing.dtype_groups``) -- into the compiled step, and
``ContinuousTrainer``/``LossScaler`` share :func:`finite_all`, the
eager twin (one jitted program, one boolean, one device_get).  On the
first non-finite step an attribution pass names WHICH parameter went
non-finite and raises :class:`NonFiniteError(param, step, kind)`.  The
``numerics.nonfinite`` chaos fail point (action
:func:`poison_action`) injects a NaN deterministically so the whole
detection path is testable; ``numerics.*`` telemetry instruments are
catalogued in ``hooks.INSTRUMENTS`` and ``/statusz`` carries a
``numerics`` row.
"""
from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Tuple

from .core import Diagnostic, rule
from .sharding import (_call_name, _file_defs_and_assigns, _is_jit_call,
                       _resolve_body)
from .trace_lint import TRACED_SCOPES

__all__ = [
    "AUDIT_SCHEMA", "THRESHOLDS",
    "audit_hlo_numerics", "numerics_audit", "save_audit", "load_audit",
    "diff_audit",
    "NonFiniteError", "check_enabled", "finite_tree", "finite_all",
    "finite_sentinel", "attribute_nonfinite", "poison_nd",
    "poison_action", "status_row",
]

# ----------------------------------------------------------------------
# dtype spelling helpers (shared by all five static rules)
# ----------------------------------------------------------------------

_HALF_NAMES = {"float16", "bfloat16", "half"}
_F32_NAMES = {"float32", "single", "float64", "double"}


def _dtype_name(node) -> Optional[str]:
    """The dtype a literal/attribute spells: ``'bfloat16'``,
    ``np.float16``, ``jnp.bfloat16`` -> its name; None otherwise."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_half_dtype(node) -> bool:
    return _dtype_name(node) in _HALF_NAMES


def _is_wide_dtype(node) -> bool:
    return _dtype_name(node) in _F32_NAMES


def _dtype_kw(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    return None


_CAST_METHODS = {"astype", "cast", "as_in_ctx", "as_type"}


def _cast_target(expr) -> Optional[str]:
    """``'half'``/``'wide'`` when ``expr`` is an explicit dtype cast
    (``x.astype(bf16)``, ``F.cast(x, dtype='float16')``); else None."""
    if not isinstance(expr, ast.Call):
        return None
    f = expr.func
    cand = None
    if isinstance(f, ast.Attribute) and f.attr in ("astype", "cast") \
            and expr.args:
        cand = expr.args[0]
    dk = _dtype_kw(expr)
    if dk is not None:
        cand = dk
    if cand is None:
        return None
    if _is_half_dtype(cand):
        return "half"
    if _is_wide_dtype(cand):
        return "wide"
    return None


def _expr_half(expr, tainted) -> bool:
    """Conservatively: does ``expr`` produce a half-precision value?

    Half flows from explicit half casts / ``dtype=`` kwargs and from
    names in ``tainted``; an explicit fp32 cast cleanses.  Mixed binops
    follow JAX promotion: half op f32 widens, half op weak Python
    scalar stays half."""
    if expr is None:
        return False
    cast = _cast_target(expr)
    if cast == "half":
        return True
    if cast == "wide":
        return False
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Attribute):
        return _expr_half(expr.value, tainted)
    if isinstance(expr, ast.BinOp):
        lh = _expr_half(expr.left, tainted)
        rh = _expr_half(expr.right, tainted)
        lw = isinstance(expr.left, ast.Constant)
        rw = isinstance(expr.right, ast.Constant)
        return (lh and (rh or rw)) or (rh and lw)
    if isinstance(expr, ast.UnaryOp):
        return _expr_half(expr.operand, tainted)
    if isinstance(expr, ast.Call):
        # dtype-preserving op/method call: half in -> half out
        if isinstance(expr.func, ast.Attribute) and \
                _expr_half(expr.func.value, tainted):
            return True
        return any(_expr_half(a, tainted) for a in expr.args)
    if isinstance(expr, (ast.Subscript, ast.Starred)):
        return _expr_half(expr.value, tainted)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return any(_expr_half(e, tainted) for e in expr.elts)
    return False


def _expr_wide(expr, tainted32) -> bool:
    """Does ``expr`` produce a deliberately-fp32 value (an explicit
    upcast or a name carrying one)?"""
    if expr is None:
        return False
    cast = _cast_target(expr)
    if cast == "wide":
        return True
    if cast == "half":
        return False
    if isinstance(expr, ast.Name):
        return expr.id in tainted32
    if isinstance(expr, ast.Attribute):
        return _expr_wide(expr.value, tainted32)
    if isinstance(expr, ast.BinOp):
        return _expr_wide(expr.left, tainted32) or \
            _expr_wide(expr.right, tainted32)
    if isinstance(expr, ast.UnaryOp):
        return _expr_wide(expr.operand, tainted32)
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Attribute) and \
                _expr_wide(expr.func.value, tainted32):
            return True
        return any(_expr_wide(a, tainted32) for a in expr.args)
    return False


def _assign_targets(node) -> List[str]:
    out = []
    targets = node.targets if isinstance(node, ast.Assign) \
        else [node.target]
    for tgt in targets:
        for n in ast.walk(tgt):
            if isinstance(n, ast.Name):
                out.append(n.id)
    return out


def _scope_taints(fn) -> Tuple[set, set]:
    """(half_tainted, f32_tainted) name sets of one function scope,
    propagated through assignments in source order (two passes to
    catch forward-flowing reuse)."""
    assigns = [n for n in ast.walk(fn)
               if isinstance(n, (ast.Assign, ast.AugAssign))]
    assigns.sort(key=lambda n: n.lineno)
    half, wide = set(), set()
    for _ in range(2):
        for node in assigns:
            value = node.value
            names = _assign_targets(node)
            if _expr_half(value, half):
                half.update(names)
                wide.difference_update(names)
            elif _expr_wide(value, wide):
                wide.update(names)
                half.difference_update(names)
    return half, wide


def _jitted_fn_nodes(tree):
    """Function defs passed to ``jax.jit`` (the perflint resolver)."""
    defs, assigns = _file_defs_and_assigns(tree)
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
            body = _resolve_body(node.args[0], defs, assigns)
            if body is not None and body[2] is not None:
                out.append(body[2])
    return out


def _traced_and_jitted_scopes(tree):
    scopes = [n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef)
              and n.name in TRACED_SCOPES]
    seen = {id(s) for s in scopes}
    for fn in _jitted_fn_nodes(tree):
        if id(fn) not in seen:
            seen.add(id(fn))
            scopes.append(fn)
    return scopes


def _leaf_name(func) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


# ----------------------------------------------------------------------
# bf16-sensitive-reduce
# ----------------------------------------------------------------------

# dtype-sensitive reductions: long accumulation chains where bf16's 8
# mantissa bits lose everything below 1/256 of the running sum
_REDUCE_NAMES = {"sum", "mean", "prod", "var", "std", "norm",
                 "softmax", "log_softmax", "logsumexp", "cumsum"}


def _has_f32_accum(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "preferred_element_type":
            return True
        if kw.arg in ("dtype", "acc_dtype") and _is_wide_dtype(kw.value):
            return True
    return False


@rule("bf16-sensitive-reduce", "ast",
      "A sum/mean/var/std/norm/softmax reduction over a half-precision "
      "value inside a traced scope with no fp32 accumulation: bf16 "
      "carries ~8 mantissa bits, so the running sum silently absorbs "
      "every addend below 1/256 of its magnitude.  Upcast first "
      "(x.astype('float32')) or pass preferred_element_type.")
def _lint_bf16_reduce(tree, path, ctx):
    for fn in _traced_and_jitted_scopes(tree):
        half, _wide = _scope_taints(fn)
        if not half:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _leaf_name(node.func)
            if name not in _REDUCE_NAMES or _has_f32_accum(node):
                continue
            # method form x.sum(): the receiver carries the dtype;
            # func form F.sum(x): the first tensor arg does
            if isinstance(node.func, ast.Attribute) and \
                    not isinstance(node.func.value, ast.Name):
                src = node.func.value
                hot = _expr_half(src, half)
            elif isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in half:
                hot = True
            else:
                hot = any(_expr_half(a, half) for a in node.args)
            if not hot:
                continue
            yield Diagnostic(
                "bf16-sensitive-reduce",
                "%s() reduces a half-precision value in traced scope "
                "%r without fp32 accumulation; bf16/fp16 running sums "
                "absorb addends below ~1/256 of their magnitude.  Did "
                "you mean x.astype('float32').%s(...) or "
                "preferred_element_type=jnp.float32?"
                % (name, fn.name, name),
                file=path, line=node.lineno)


# ----------------------------------------------------------------------
# unscaled-half-loss
# ----------------------------------------------------------------------

# any of these names in the enclosing scope marks the loss as scaled /
# scaling-aware (LossScaler instance, amp.scale_loss, trainer AMP init)
_SCALE_MARKERS = {"LossScaler", "scale_loss", "loss_scale", "amp",
                  "loss_scaler", "unscale", "init_trainer"}


def _scope_mentions_scaling(fn) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Name) and n.id in _SCALE_MARKERS:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _SCALE_MARKERS:
            return True
    return False


@rule("unscaled-half-loss", "ast",
      "A half-precision loss fed to backward() with no LossScaler/"
      "amp.scale_loss in the dataflow: fp16 gradients underflow to "
      "zero unscaled (bf16 shares fp32's exponent range; fp16 does "
      "not).  Wrap with amp.scale_loss(loss, trainer) or a LossScaler.")
def _lint_unscaled_half_loss(tree, path, ctx):
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        half, _wide = _scope_taints(fn)
        if not half or _scope_mentions_scaling(fn):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hot = False
            if isinstance(f, ast.Attribute) and f.attr == "backward" \
                    and _expr_half(f.value, half):
                hot = True          # loss.backward()
            elif _leaf_name(f) == "backward" and \
                    any(_expr_half(a, half) for a in node.args):
                hot = True          # autograd.backward(loss)
            if not hot:
                continue
            yield Diagnostic(
                "unscaled-half-loss",
                "backward() on a half-precision loss in %r with no "
                "loss scaling in scope; fp16 grads underflow unscaled. "
                " Did you mean amp.scale_loss(loss, trainer).backward()"
                " or a LossScaler?" % fn.name,
                file=path, line=node.lineno)


# ----------------------------------------------------------------------
# half-optimizer-state
# ----------------------------------------------------------------------

import re as _re

_ARRAY_CREATORS = {"zeros", "ones", "full", "empty", "zeros_like",
                   "ones_like", "full_like", "array"}
_STATE_FN_RE = _re.compile(r"create_state|_state$", _re.I)
_STATE_NAME_RE = _re.compile(
    r"(mom(entum)?|var(iance)?|mean|ema|avg|state|vhat|mhat|velocity|"
    r"accum)", _re.I)


@rule("half-optimizer-state", "ast",
      "Optimizer state / EMA buffer created in fp16/bf16: momentum and "
      "variance accumulate per-step deltas ~1/1000 of their magnitude, "
      "which a half-precision store absorbs entirely.  Keep state fp32 "
      "(the master-weights discipline) and cast at apply time.")
def _lint_half_optimizer_state(tree, path, ctx):
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        in_state_fn = bool(_STATE_FN_RE.search(fn.name))
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.Return)):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and _leaf_name(value.func) in _ARRAY_CREATORS):
                continue
            dk = _dtype_kw(value)
            if dk is None or not _is_half_dtype(dk):
                continue
            if isinstance(node, ast.Return):
                statey = in_state_fn
            else:
                names = _assign_targets(node)
                attrs = [t.attr for tgt in (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target])
                    for t in ast.walk(tgt) if isinstance(t, ast.Attribute)]
                statey = in_state_fn or any(
                    _STATE_NAME_RE.search(nm) for nm in names + attrs)
            if not statey:
                continue
            yield Diagnostic(
                "half-optimizer-state",
                "%s(dtype=%s) creates optimizer state in half "
                "precision in %r; per-step deltas underflow the store. "
                " Did you mean dtype='float32' (cast at apply time)?"
                % (_leaf_name(value.func), _dtype_name(dk), fn.name),
                file=path, line=value.lineno)


# ----------------------------------------------------------------------
# implicit-downcast
# ----------------------------------------------------------------------

# bf16 resolves ~2^-8 relative; a Python float below this absolute
# threshold next to O(1) half activations is at absorption risk
_WEAK_CONST_MAX = 2.0 ** -8


@rule("implicit-downcast", "ast",
      "An fp32 value or small Python-float constant narrowed by "
      "mixed-dtype promotion landing in half precision: a weak-typed "
      "scalar with a bf16 array stays bf16 (x + 1e-8 is exactly x), "
      "and .astype(half) on a deliberate fp32 upcast throws the "
      "precision away.  Materialize constants at fp32 and keep the "
      "compute wide until the final cast.")
def _lint_implicit_downcast(tree, path, ctx):
    for fn in _traced_and_jitted_scopes(tree):
        half, wide = _scope_taints(fn)
        for node in ast.walk(fn):
            # form (a): tiny weak float absorbed by a half operand
            if isinstance(node, ast.BinOp) and \
                    isinstance(node.op, (ast.Add, ast.Sub)) and half:
                for c, other in ((node.left, node.right),
                                 (node.right, node.left)):
                    if not (isinstance(c, ast.Constant)
                            and isinstance(c.value, float)):
                        continue
                    if not (0.0 < abs(c.value) < _WEAK_CONST_MAX):
                        continue
                    if _expr_half(other, half):
                        yield Diagnostic(
                            "implicit-downcast",
                            "python float %g with a half-precision "
                            "operand in traced scope %r is weak-typed: "
                            "promotion lands bf16/fp16 and the "
                            "constant is absorbed (bf16 resolves "
                            "~2^-8).  Did you mean to upcast first "
                            "(x.astype('float32') + %g)?"
                            % (c.value, fn.name, c.value),
                            file=path, line=node.lineno)
            # form (b): a deliberate fp32 value cast back down to half
            if isinstance(node, ast.Call) and wide:
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "astype" \
                        and node.args and _is_half_dtype(node.args[0]) \
                        and _expr_wide(f.value, wide):
                    yield Diagnostic(
                        "implicit-downcast",
                        ".astype(%r) narrows a deliberate fp32 value "
                        "back to half precision in traced scope %r; "
                        "keep the accumulation wide until the final "
                        "output cast" % (_dtype_name(node.args[0]),
                                         fn.name),
                        file=path, line=node.lineno)


# ----------------------------------------------------------------------
# nonfinite-guard-missing
# ----------------------------------------------------------------------

_NONFINITE_FNS = {"log", "log2", "log10", "rsqrt", "reciprocal"}
_GUARD_CALLS = {"maximum", "clip", "clamp", "abs", "exp", "softmax",
                "sigmoid", "softplus", "square", "relu", "where"}
_EPS_NAME_RE = _re.compile(r"eps|epsilon|delta|tiny", _re.I)


def _arg_guarded(expr) -> bool:
    """Is the argument expression bounded away from the pole -- an eps
    addition, a clip/maximum/abs/exp wrap, or a literal?"""
    if isinstance(expr, ast.Constant):
        return True
    for n in ast.walk(expr):
        if isinstance(n, ast.BinOp) and isinstance(n.op, (ast.Add,
                                                          ast.Sub)):
            for side in (n.left, n.right):
                if isinstance(side, ast.Constant) and \
                        isinstance(side.value, (int, float)) and \
                        side.value != 0:
                    return True
                if isinstance(side, ast.Name) and \
                        _EPS_NAME_RE.search(side.id):
                    return True
                if isinstance(side, ast.Attribute) and \
                        _EPS_NAME_RE.search(side.attr):
                    return True
        if isinstance(n, ast.Call) and _leaf_name(n.func) in _GUARD_CALLS:
            return True
        if isinstance(n, ast.Name) and _EPS_NAME_RE.search(n.id):
            return True
    return False


@rule("nonfinite-guard-missing", "ast",
      "log/rsqrt/reciprocal on an unbounded input inside a traced "
      "scope with no eps/clip guard in the expression: the first NaN "
      "factory every divergence postmortem finds.  Guard the argument "
      "(log(x + eps), rsqrt(var + eps), clip/maximum first).")
def _lint_nonfinite_guard(tree, path, ctx):
    for fn in _traced_and_jitted_scopes(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _leaf_name(node.func)
            if name not in _NONFINITE_FNS or not node.args:
                continue
            if any(kw.arg is not None and _EPS_NAME_RE.search(kw.arg)
                   for kw in node.keywords):
                continue
            if _arg_guarded(node.args[0]):
                continue
            yield Diagnostic(
                "nonfinite-guard-missing",
                "%s() on an unguarded input in traced scope %r can go "
                "non-finite at the pole.  Did you mean %s(x + eps) or "
                "a maximum/clip guard?" % (name, fn.name, name),
                file=path, line=node.lineno)


# ======================================================================
# Compiled layer: the HLO precision auditor
# ======================================================================

AUDIT_SCHEMA = "mxnumerics.audit.v1"

_HALF_HLO = {"bf16", "f16"}

# convert-storm fires when convert-op bytes reach this share of the
# executable's byte traffic; the dot/reduce advisories fire on presence
# (their share metrics gate growth via diff_audit)
THRESHOLDS = {
    "convert_share": 0.15,
}


def audit_hlo_numerics(text: str) -> Dict:
    """Raw precision counters of one compiled module's HLO text.

    Walks every computation once (fusion bodies inclusive -- the dtype
    hazards live on the instructions themselves, wherever XLA fused
    them) and counts: convert-op bytes with ``op_name`` provenance,
    dot/conv ops whose output (accumulator) dtype is a half operand
    dtype, and reduce ops whose in+out dtypes are both half.
    """
    from ..profiling import hlo

    _entry, comps, _refs = hlo.parse_module(text)
    out = {
        "bytes_total": 0,
        "convert_bytes": 0, "convert_ops": {},       # op_name -> bytes
        "half_dot_bytes": 0, "mxu_bytes": 0,
        "half_dots": {},                             # op_name -> bytes
        "half_reduce_bytes": 0, "reduce_bytes": 0,
        "half_reduces": {},                          # op_name -> bytes
    }
    for _name, instrs in comps.items():
        for ins in instrs:
            op = ins.opcode
            if op in hlo._SKIP or op in ("fusion", "while", "conditional",
                                         "call") or op.startswith("async-"):
                continue
            nbytes = hlo._nbytes(ins.operand_shapes) + \
                hlo._nbytes(ins.out_shapes)
            out["bytes_total"] += nbytes
            key = ins.op_name or op
            if op == "convert":
                out["convert_bytes"] += nbytes
                out["convert_ops"][key] = \
                    out["convert_ops"].get(key, 0) + nbytes
            elif op in ("dot", "convolution"):
                out["mxu_bytes"] += nbytes
                half_in = {dt for dt, _dims in ins.operand_shapes
                           if dt in _HALF_HLO}
                out_half = any(dt in half_in
                               for dt, _dims in ins.out_shapes)
                if half_in and out_half:
                    out["half_dot_bytes"] += nbytes
                    out["half_dots"][key] = \
                        out["half_dots"].get(key, 0) + nbytes
            elif op in ("reduce", "reduce-window"):
                out["reduce_bytes"] += nbytes
                # pred-typed reductions (any/all -- e.g. the sentinel's
                # own isfinite fold) carry no accumulation precision
                dts = [dt for dt, _dims in list(ins.operand_shapes)
                       + list(ins.out_shapes) if dt != "pred"]
                if dts and all(dt in _HALF_HLO for dt in dts):
                    out["half_reduce_bytes"] += nbytes
                    out["half_reduces"][key] = \
                        out["half_reduces"].get(key, 0) + nbytes
    return out


def _merge_counters(agg: Dict, cur: Dict):
    for k, v in cur.items():
        if isinstance(v, dict):
            slot = agg.setdefault(k, {})
            for nm, b in v.items():
                slot[nm] = slot.get(nm, 0) + b
        else:
            agg[k] = agg.get(k, 0) + v


def _metrics_of(counters: Dict) -> Dict:
    total = counters["bytes_total"] or 1
    mxu = counters["mxu_bytes"] or 1
    red = counters["reduce_bytes"] or 1
    return {
        "convert_share": round(counters["convert_bytes"] / total, 4),
        "half_accum_dot_share": round(
            counters["half_dot_bytes"] / mxu, 4),
        "half_reduce_share": round(
            counters["half_reduce_bytes"] / red, 4),
        "bytes_total": counters["bytes_total"],
    }


def _top(d: Dict, n=3) -> List[str]:
    return [nm for nm, _b in sorted(d.items(), key=lambda kv: -kv[1])[:n]]


def _advisories_for(label: str, metrics: Dict, counters: Dict,
                    thresholds: Dict) -> List[Dict]:
    adv = []
    if metrics["half_accum_dot_share"] > 0:
        names = _top(counters["half_dots"])
        adv.append({
            "kind": "half-accum-dot",
            "share": metrics["half_accum_dot_share"],
            "op_names": names,
            "message": "%.0f%% of %r's MXU bytes are dot/conv ops "
                       "accumulating in their half-precision operand "
                       "type (top scopes: %s); pass "
                       "preferred_element_type=jnp.float32 so the MXU "
                       "accumulates fp32"
                       % (100 * metrics["half_accum_dot_share"], label,
                          ", ".join(names) or "<unnamed>"),
        })
    if metrics["convert_share"] >= thresholds["convert_share"]:
        names = _top(counters["convert_ops"])
        adv.append({
            "kind": "convert-storm",
            "share": metrics["convert_share"],
            "op_names": names,
            "message": "%.0f%% of %r's memory traffic is dtype "
                       "converts (top scopes: %s) -- a mixed-precision "
                       "boundary is thrashing; align dtypes across the "
                       "op chain or move the cast outside the hot loop"
                       % (100 * metrics["convert_share"], label,
                          ", ".join(names) or "<unnamed>"),
        })
    if metrics["half_reduce_share"] > 0:
        names = _top(counters["half_reduces"])
        adv.append({
            "kind": "half-reduce",
            "share": metrics["half_reduce_share"],
            "op_names": names,
            "message": "%.0f%% of %r's reduction bytes accumulate "
                       "entirely in bf16/fp16 (top scopes: %s); "
                       "upcast the reduction input to fp32 -- the "
                       "static bf16-sensitive-reduce rule names the "
                       "source sites"
                       % (100 * metrics["half_reduce_share"], label,
                          ", ".join(names) or "<unnamed>"),
        })
    adv.sort(key=lambda a: -a["share"])
    return adv


def numerics_audit(thresholds=None) -> Dict:
    """Audit every executable the profiling capture surface registered
    for precision hazards; same walk as ``perf.perf_audit`` (lowering
    hits jax's executable cache).  Returns the ``mxnumerics.audit.v1``
    artifact CI diffs against ``ci/numerics_baseline.json``."""
    import jax
    from ..profiling import store

    th = dict(THRESHOLDS)
    if thresholds:
        th.update(thresholds)
    merged: Dict[str, Dict] = {}
    for label, compiled in store.compiled_executables():
        try:
            text = compiled.as_text()
        except Exception:
            continue
        counters = audit_hlo_numerics(text)
        if label in merged:
            _merge_counters(merged[label], counters)
        else:
            merged[label] = counters
    execs = {}
    for label, counters in merged.items():
        metrics = _metrics_of(counters)
        execs[label] = {
            "metrics": metrics,
            "advisories": _advisories_for(label, metrics, counters, th),
        }
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    ranked = sorted(
        (dict(a, executable=label)
         for label, e in execs.items() for a in e["advisories"]),
        key=lambda a: -a["share"])
    return {
        "schema": AUDIT_SCHEMA,
        "backend": backend,
        "thresholds": th,
        "executables": execs,
        "advisories": ranked,
    }


def save_audit(path: str, audit=None) -> Dict:
    """Write the current numerics audit as JSON (the artifact CI diffs
    against the committed ``ci/numerics_baseline.json``)."""
    audit = audit if audit is not None else numerics_audit()
    with open(path, "w") as f:
        json.dump(audit, f, indent=1, sort_keys=True)
        f.write("\n")
    return audit


def load_audit(path: str) -> Dict:
    with open(path) as f:
        data = json.load(f)
    if data.get("schema") != AUDIT_SCHEMA:
        raise ValueError("%s is not a %s artifact (schema=%r)"
                         % (path, AUDIT_SCHEMA, data.get("schema")))
    return data


def _audit_tol() -> float:
    try:
        return float(os.environ.get("MXNET_TPU_NUMERICS_AUDIT_TOL",
                                    "0.02"))
    except ValueError:
        return 0.02


# share metrics where GROWTH is a precision regression
_GROWTH_METRICS = ("convert_share", "half_accum_dot_share",
                   "half_reduce_share")


def diff_audit(baseline: Dict, current: Dict,
               tol: Optional[float] = None) -> List[Diagnostic]:
    """Precision drift of ``current`` vs the blessed ``baseline``:

    - an advisory KIND the baseline doesn't carry for that executable
      (or a brand-new executable auditing with advisories) -> error;
    - a share metric (convert / half-accum-dot / half-reduce) grown
      more than ``tol`` (absolute; default
      ``MXNET_TPU_NUMERICS_AUDIT_TOL`` = 0.02) -> error.

    Improvements (smaller shares, fewer advisories) pass silently --
    re-bless with :func:`save_audit` after an intentional change."""
    tol = _audit_tol() if tol is None else tol
    diags: List[Diagnostic] = []
    base_ex = baseline.get("executables", {})
    for label, cur in sorted(current.get("executables", {}).items()):
        base = base_ex.get(label, {"metrics": {}, "advisories": []})
        blessed = {a["kind"] for a in base.get("advisories", [])}
        for a in cur.get("advisories", []):
            if a["kind"] not in blessed:
                diags.append(Diagnostic(
                    "numerics-drift",
                    "executable %r gained unblessed %r advisory "
                    "(precision share %.1f%%): %s -- fix the "
                    "regression or re-bless via analysis.numerics."
                    "save_audit" % (label, a["kind"], 100 * a["share"],
                                    a["message"]),
                    node=label))
        bm = base.get("metrics", {})
        cm = cur.get("metrics", {})
        for m in _GROWTH_METRICS:
            b, c = bm.get(m, 0.0), cm.get(m, 0.0)
            if c > b + tol:
                diags.append(Diagnostic(
                    "numerics-drift",
                    "executable %r: %s grew %.4f -> %.4f (tolerance "
                    "%.4f); the compiled step lost precision headroom "
                    "vs what the baseline blesses" % (label, m, b, c,
                                                      tol),
                    node=label))
    return diags


@rule("numerics-drift", "compiled",
      "A registered executable's precision metrics (half-accumulated "
      "dots, convert-storm bytes, bf16 reductions) drifted past the "
      "committed ci/numerics_baseline.json -- a named, gated precision "
      "regression.  Gate: mxlint --numerics-diff.")
def _rule_numerics_drift(baseline, current):
    return diff_audit(baseline, current)


# ======================================================================
# Runtime layer: the non-finite sentinel
# ======================================================================

# THE flag the hot paths check: one module-attribute read when off.
_CHECK = os.environ.get("MXNET_TPU_NUMERICS_CHECK", "0") != "0"

# sentinel state the /statusz row reads
_STATE = {"checks": 0, "nonfinite": 0, "last": None}


def check_enabled() -> bool:
    """Is the non-finite sentinel armed (``MXNET_TPU_NUMERICS_CHECK``)?"""
    return _CHECK


def _set_check(flag):
    """Test/scenario hook: flip the sentinel without re-importing."""
    global _CHECK
    prev = _CHECK
    _CHECK = bool(flag)
    return prev


class NonFiniteError(RuntimeError):
    """A gradient (or the loss) went NaN/Inf; ``param`` names the first
    offender, ``step`` the update count, ``kind`` is ``'nan'`` or
    ``'inf'``.  Raised by the sentinel AFTER the framework state was
    restored to the pre-step values (the branchless overflow-skip keeps
    old weights on a non-finite step), so a handler can lower the lr /
    re-seed data and continue."""

    def __init__(self, param, step, kind):
        super().__init__(
            "non-finite gradient: %s in parameter %r at step %s "
            "(weights kept at their pre-step values; see docs/"
            "numerics.md)" % (kind, param, step))
        self.param = param
        self.step = step
        self.kind = kind


def _float_leaves(leaves):
    import jax.numpy as jnp
    return [x for x in leaves
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype,
                                                      jnp.floating)]


def finite_tree(leaves):
    """ONE fused in-graph isfinite-reduction over ``leaves``: bucket by
    dtype (``bucketing.dtype_groups``), flatten each bucket into one
    buffer, reduce each with a single ``all(isfinite)``, AND the
    per-bucket booleans.  Traceable -- TrainStep folds this into the
    compiled step, so the clean path costs one boolean output and no
    extra host sync.  Non-float leaves (int step counters) are skipped."""
    import jax.numpy as jnp
    from .. import bucketing
    fl = _float_leaves(leaves)
    if not fl:
        return jnp.bool_(True)
    ok = jnp.bool_(True)
    for _dt, idxs in bucketing.dtype_groups(fl):
        buf = bucketing.flatten_group(fl, idxs, jnp)
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(buf)))
    return ok


# eager twin: one cached jitted program per (shape, dtype) signature,
# bounded -- a sentinel wrapped around ever-changing shapes must not
# itself leak one executable per novel signature (the very hazard the
# memory pass's unbounded-shape-cache rule lints for)
_FUSED_CACHE: Dict[tuple, object] = {}
_FUSED_CACHE_CAP = 64


def finite_all(arrays):
    """The eager twin of :func:`finite_tree`: ONE jitted fused finite
    check over the bucketed array set, returning a device boolean (the
    caller decides when to pay the single device_get).  The jitted
    program is cached per (shape, dtype) signature -- steady-state cost
    is one dispatch, no per-array host round trips."""
    import jax
    arrs = [a._data if hasattr(a, "_data") else a for a in arrays]
    arrs = _float_leaves(arrs)
    if not arrs:
        import jax.numpy as jnp
        return jnp.bool_(True)
    key = tuple((tuple(a.shape), str(a.dtype)) for a in arrs)
    fn = _FUSED_CACHE.get(key)
    if fn is None:
        while len(_FUSED_CACHE) >= _FUSED_CACHE_CAP:
            _FUSED_CACHE.pop(next(iter(_FUSED_CACHE)))
        fn = jax.jit(lambda *xs: finite_tree(list(xs)))
        _FUSED_CACHE[key] = fn
    return fn(*arrs)


def attribute_nonfinite(named) -> Optional[Tuple[str, str]]:
    """The attribution pass: scan ``(name, array)`` pairs host-side
    (failure path only) and return ``(name, kind)`` of the first
    non-finite entry -- NaN reported before Inf when both occur."""
    import numpy as np
    first_inf = None
    for name, a in named:
        x = a._data if hasattr(a, "_data") else a
        x = np.asarray(x)
        if not np.issubdtype(x.dtype, np.floating):
            continue
        if np.isnan(x).any():
            return name, "nan"
        if first_inf is None and np.isinf(x).any():
            first_inf = (name, "inf")
    return first_inf


def note_check(seconds):
    """Book one sentinel check (the /statusz counter + the
    ``numerics.checks`` / ``numerics.check_time`` instruments)."""
    _STATE["checks"] += 1
    from .. import telemetry as _telemetry
    if _telemetry._ENABLED:
        _telemetry.hooks.numerics_check(seconds)


def record_nonfinite(param, step, kind):
    """Book a detected non-finite step: telemetry + the /statusz row."""
    _STATE["nonfinite"] += 1
    _STATE["last"] = {"param": param, "step": step, "kind": kind}
    from .. import telemetry as _telemetry
    if _telemetry._ENABLED:
        _telemetry.hooks.numerics_nonfinite(param, step, kind)


def finite_sentinel(named, step=None):
    """Check named gradients/params for non-finites in ONE fused jitted
    reduction + ONE boolean device_get; raise :class:`NonFiniteError`
    naming the first offender.  Disarmed (the default): one module-flag
    check, the arguments are never touched.

    ``named``: iterable of ``(name, array)`` pairs (NDArray or jax).
    Returns True on a clean pass."""
    if not _CHECK:
        return True
    import time

    import numpy as np
    named = list(named)
    ok_dev = finite_all([a for _n, a in named])
    t0 = time.perf_counter()
    ok = bool(np.asarray(ok_dev))
    note_check(time.perf_counter() - t0)
    if ok:
        return True
    hit = attribute_nonfinite(named)
    param, kind = hit if hit is not None else ("<unattributed>",
                                               "nonfinite")
    record_nonfinite(param, step, kind)
    raise NonFiniteError(param, step, kind)


# -- chaos integration -------------------------------------------------

def poison_action(ctx):
    """The ``numerics.nonfinite`` chaos action: instead of raising,
    mark the caller's ``box`` so IT poisons the in-flight batch with a
    NaN -- the fault then flows through forward/backward and must be
    caught by the sentinel, not by the injector.  Arm with::

        chaos.on("numerics.nonfinite", numerics.poison_action, nth=3)
    """
    box = ctx.get("box")
    if box is not None:
        box["poison"] = True


def poison_nd(x):
    """NaN-poison element 0 of a (float) array/NDArray, preserving
    wrapper type -- the deterministic fault ``poison_action`` asks the
    training step to inject into its own batch."""
    import jax.numpy as jnp
    data = x._data if hasattr(x, "_data") else x
    if not jnp.issubdtype(data.dtype, jnp.floating):
        return x
    flat = data.reshape(-1).at[0].set(jnp.nan)
    poisoned = flat.reshape(data.shape)
    if hasattr(x, "_data"):
        from ..ndarray import NDArray
        return NDArray(poisoned)
    return poisoned


def status_row() -> Dict:
    """The ``/statusz`` numerics row: sentinel arm state, checks run,
    non-finite steps seen, and the last attribution."""
    return {"armed": _CHECK, "checks": _STATE["checks"],
            "nonfinite": _STATE["nonfinite"], "last": _STATE["last"]}
