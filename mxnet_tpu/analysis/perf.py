"""perflint (ISSUE 10 tentpole): TPU performance linter + compiled-HLO
efficiency auditor.

PR 6 measures per-HLO cost and PRs 1/5/7 lint for *correctness*
(trace safety, concurrency, sharding); nothing named the perf hazards
ROADMAP item 2 is chasing (ResNet-50 MFU 0.248 -> >=0.32).  This pass
does, in the same two layers as the sharding sanitizer:

**Static layer** (AST, under the PR-1 rule framework; runs in
``mxlint --self``):

- ``layout-hostile-conv``: a Conv/Pool layer constructed with the
  *silent* NCHW default in model code.  The framework has a complete
  channels-last path (``layout="NHWC"``, ``tests/test_layout.py``) and
  on TPU the NCHW tax is real transpose traffic around every conv
  (docs/perf_resnet50.md); construction sites must choose a layout
  explicitly -- thread a ``layout`` parameter (model_zoo does) or pass
  the literal deliberately.
- ``pad-waste``: a literal layer dim (Dense units, Conv channels,
  Embedding width) not aligned to the TPU tile -- 128 lanes in the
  minor dim, 8 (f32) / 16 (bf16) sublanes in the second-minor.  The
  waste fraction is computed and a did-you-mean dim suggested.
- ``python-loop-unroll``: a Python ``for`` over ``range(N)`` or a
  homogeneous layer stack inside a traced scope
  (``hybrid_forward``/``_forward_impl``) or a jitted step function --
  the loop unrolls N copies into the trace, scaling compile time and
  program size linearly where ``jax.lax.scan``/``fori_loop`` compiles
  once.
- ``scalar-recompile``: a per-step-varying Python scalar (``lr``,
  ``t``, ``loss_scale``, ...) passed by keyword into an op invocation
  when that name is not threaded dynamically by the eager engine
  (``ndarray._DYNAMIC_PARAMS``) -- the static call-site twin of PR 1's
  registry-level retrace auditor: every distinct value recompiles.
- ``eager-in-step-loop``: an un-jitted eager ``nd.*`` op dispatched
  inside a detected training loop -- per-step Python dispatch the
  compiled step (or a ``bulk`` scope) should absorb.

**Compiled layer**: :func:`perf_audit` walks PR 6's persistent
``profiling.store.executables()`` registry, lowers each entry (hitting
jax's executable cache) and emits ranked advisories from the existing
category/roofline machinery -- transpose/layout share above threshold,
elementwise bytes XLA failed to fuse, actual-vs-tile-padded shape waste
on the MXU ops, and memory-bound executables whose arithmetic intensity
sits far below the device ridge.  Every advisory names the executable,
the HLO category, ``op_name`` provenance, and its estimated cost
share.  ``save_audit``/``diff_audit`` + the committed
``ci/perf_baseline.json`` gate drift exactly like the sharding
baseline: ``mxlint --perf-diff BASE CUR`` errors on growth, passes on
improvement (rule ``perf-drift``; CI stage ``perflint``;
docs/perf_lint.md).
"""
from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional

from .core import Diagnostic, WARNING, rule
from .retrace import VARYING_PARAM_NAMES, eager_dynamic_params
from .sharding import (_call_name, _file_defs_and_assigns, _is_jit_call,
                       _resolve_body)
from .trace_lint import TRACED_SCOPES

__all__ = [
    "AUDIT_SCHEMA", "THRESHOLDS",
    "audit_hlo_text", "perf_audit", "save_audit", "load_audit",
    "diff_audit",
]

# ----------------------------------------------------------------------
# TPU tiling constants (see /opt accelerator guide: vector memory is
# tiled (sublane, lane) = (8, 128) for 4-byte types; 2-byte types pack
# 16 sublanes, 1-byte types 32)
# ----------------------------------------------------------------------

TILE_LANE = 128
SUBLANE_F32 = 8
SUBLANE_BF16 = 16
# literal dims below this are structural (class counts, stem widths) --
# rounding them up changes the task, not the padding
_PAD_MIN_DIM = 16

# layer constructors whose dim/layout choices the static rules inspect
_DIM_LAYERS = {"Dense": 0, "Conv1D": 0, "Conv2D": 0, "Conv3D": 0,
               "Embedding": 1}
_DIM_KWARGS = {"units", "channels", "output_dim"}
_LAYOUT_LAYERS = {
    "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose", "Conv1DTranspose",
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
    "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
}
# iterables that read as a homogeneous layer/step stack
import re as _re
_STACK_NAME_RE = _re.compile(r"(layers|blocks|cells|steps|stack)s?$",
                             _re.I)
_MIN_UNROLL = 4


def _ceil_to(d, g):
    return ((d + g - 1) // g) * g


# ----------------------------------------------------------------------
# layout-hostile-conv
# ----------------------------------------------------------------------

@rule("layout-hostile-conv", "ast",
      "A Conv/Pool layer constructed with the silent NCHW default in "
      "model code; the channels-last (NHWC) path exists and NCHW costs "
      "transpose traffic around every conv on TPU.  Thread a layout "
      "parameter (model_zoo idiom) or pass layout= explicitly.")
def _lint_layout_hostile(tree, path, ctx):
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _call_name(node) in _LAYOUT_LAYERS):
            continue
        kwnames = {kw.arg for kw in node.keywords}
        if "layout" in kwnames:
            continue
        if None in kwnames:
            continue      # a **kwargs splat may carry layout; not decidable
        yield Diagnostic(
            "layout-hostile-conv",
            "%s constructed without an explicit layout= relies on the "
            "silent NCHW default; a channels-last path exists "
            "(layout=\"NHWC\") and on TPU the NCHW tax is transpose "
            "traffic around every conv.  Thread a layout parameter or "
            "pass the literal deliberately (docs/perf_lint.md)"
            % _call_name(node),
            file=path, line=node.lineno)


# ----------------------------------------------------------------------
# pad-waste
# ----------------------------------------------------------------------

def _literal_dim(call: ast.Call) -> Optional[int]:
    name = _call_name(call)
    pos = _DIM_LAYERS.get(name)
    cand = None
    if pos is not None and len(call.args) > pos:
        cand = call.args[pos]
    for kw in call.keywords:
        if kw.arg in _DIM_KWARGS:
            cand = kw.value
    if isinstance(cand, ast.Constant) and isinstance(cand.value, int):
        return cand.value
    return None


@rule("pad-waste", "ast",
      "A literal layer dim not aligned to the TPU tile (lane 128, "
      "sublane 8 f32 / 16 bf16): XLA pads the dim up and the pad "
      "fraction is dead MXU/VPU work.  Round the dim to the suggested "
      "tile multiple, or suppress where the dim is semantic (class "
      "count, reference architecture).")
def _lint_pad_waste(tree, path, ctx):
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _call_name(node) in _DIM_LAYERS):
            continue
        d = _literal_dim(node)
        if d is None or d < _PAD_MIN_DIM or d % SUBLANE_F32 == 0:
            continue
        pad8 = _ceil_to(d, SUBLANE_F32)
        pad128 = _ceil_to(d, TILE_LANE)
        waste8 = (pad8 - d) / pad8
        waste128 = (pad128 - d) / pad128
        # suggest the lane multiple when it costs <= 15% extra over the
        # literal; otherwise the cheap sublane fix
        suggest = pad128 if (pad128 - d) / d <= 0.15 else pad8
        yield Diagnostic(
            "pad-waste",
            "%s dim %d is not a multiple of the TPU sublane (8 f32 / "
            "16 bf16): pads to %d sublanes (%.1f%% waste) and %d lanes "
            "(%.1f%% waste); did you mean %d?"
            % (_call_name(node), d, pad8, 100 * waste8, pad128,
               100 * waste128, suggest),
            file=path, line=node.lineno)


# ----------------------------------------------------------------------
# python-loop-unroll
# ----------------------------------------------------------------------

def _jitted_fn_nodes(tree):
    """Function defs in ``tree`` that are passed to ``jax.jit`` --
    their bodies are traced, so Python loops there unroll."""
    defs, assigns = _file_defs_and_assigns(tree)
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
            body = _resolve_body(node.args[0], defs, assigns)
            if body is not None and body[2] is not None:
                out.append(body[2])
    return out


def _own_loops(fn):
    """For loops lexically in ``fn``'s body, nested defs excluded
    (their loops belong to another trace decision)."""
    stack = list(fn.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.For):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _range_trip(it) -> Optional[int]:
    if not (isinstance(it, ast.Call) and _call_name(it) == "range"):
        return None
    args = it.args
    lits = [a.value for a in args
            if isinstance(a, ast.Constant) and isinstance(a.value, int)]
    if len(lits) != len(args) or not args:
        return None
    if len(lits) == 1:
        return lits[0]
    if len(lits) >= 2:
        return lits[1] - lits[0]
    return None


def _calls_loop_target(loop) -> bool:
    if not isinstance(loop.target, ast.Name):
        return False
    tgt = loop.target.id
    for n in ast.walk(loop):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Name) and f.id == tgt:
                return True
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == tgt:
                return True
    return False


def _iter_stack_name(it) -> Optional[str]:
    base = it
    if isinstance(base, ast.Call) and isinstance(base.func, ast.Attribute) \
            and base.func.attr in ("values", "items"):
        base = base.func.value
    if isinstance(base, ast.Attribute):
        name = base.attr
    elif isinstance(base, ast.Name):
        name = base.id
    else:
        return None
    return name if _STACK_NAME_RE.search(name) else None


@rule("python-loop-unroll", "ast",
      "A Python for over range(N)/a homogeneous layer stack inside a "
      "traced scope (hybrid_forward/_forward_impl or a jitted step "
      "fn): the loop unrolls N copies into the trace -- compile time "
      "and program size scale linearly; jax.lax.scan/fori_loop over "
      "stacked params compiles the body once.")
def _lint_loop_unroll(tree, path, ctx):
    scopes = [n for n in ast.walk(tree)
              if isinstance(n, ast.FunctionDef)
              and n.name in TRACED_SCOPES]
    seen = {id(s) for s in scopes}
    for fn in _jitted_fn_nodes(tree):
        if id(fn) not in seen:
            seen.add(id(fn))
            scopes.append(fn)
    for fn in scopes:
        for loop in _own_loops(fn):
            trip = _range_trip(loop.iter)
            if trip is not None and trip >= _MIN_UNROLL:
                yield Diagnostic(
                    "python-loop-unroll",
                    "python for over range(%d) inside traced scope %r "
                    "unrolls %d copies of the body into the trace; use "
                    "jax.lax.fori_loop/scan so the body compiles once"
                    % (trip, fn.name, trip),
                    file=path, line=loop.lineno)
                continue
            stack = _iter_stack_name(loop.iter)
            if stack is not None and _calls_loop_target(loop):
                yield Diagnostic(
                    "python-loop-unroll",
                    "python for over homogeneous stack %r inside "
                    "traced scope %r unrolls one body copy per layer "
                    "into the trace; stack the per-layer params and "
                    "jax.lax.scan the body once" % (stack, fn.name),
                    file=path, line=loop.lineno)


# ----------------------------------------------------------------------
# scalar-recompile
# ----------------------------------------------------------------------

def _chain(func) -> List[str]:
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def _is_op_invoke(func) -> bool:
    parts = _chain(func)
    if not parts:
        return False
    if parts[0] in ("F", "nd", "sym"):
        return len(parts) > 1
    return len(parts) > 2 and parts[0] == "mx" and parts[1] in ("nd", "sym")


@rule("scalar-recompile", "ast",
      "A per-step-varying Python scalar (lr/t/loss_scale/...) passed "
      "by keyword into an op invocation when the eager engine does not "
      "thread that name dynamically (ndarray._DYNAMIC_PARAMS) -- the "
      "param is baked into the compile-cache key and every distinct "
      "value recompiles.  The static call-site twin of the retrace "
      "auditor.")
def _lint_scalar_recompile(tree, path, ctx):
    try:
        dynamic = set(eager_dynamic_params())
    except Exception:
        dynamic = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_op_invoke(node.func)):
            continue
        for kw in node.keywords:
            if kw.arg not in VARYING_PARAM_NAMES or kw.arg in dynamic:
                continue
            if isinstance(kw.value, ast.Constant):
                continue      # a literal is one cache entry, not a leak
            yield Diagnostic(
                "scalar-recompile",
                "op call passes varying scalar %r=%s outside the eager "
                "engine's dynamic set %s; each distinct value is a new "
                "compile-cache key (fresh XLA executable per step).  "
                "Add the name to ndarray._DYNAMIC_PARAMS or thread it "
                "as a tensor input"
                % (kw.arg, ast.unparse(kw.value), sorted(dynamic)),
                file=path, line=node.lineno)


# ----------------------------------------------------------------------
# eager-in-step-loop
# ----------------------------------------------------------------------

# ingest/sync entry points, not per-step compute dispatch
_EAGER_EXEMPT = {"array", "NDArray", "waitall", "save", "load"}


def _is_eager_nd_call(func) -> bool:
    parts = _chain(func)
    if len(parts) < 2:
        return False
    if parts[0] == "nd" or (len(parts) > 2 and parts[0] == "mx"
                            and parts[1] == "nd"):
        leaf = parts[-1]
        return leaf not in _EAGER_EXEMPT and not leaf[:1].isupper()
    return False


def _is_train_loop(loop) -> bool:
    """A loop whose body dispatches a train step (bare ``step(...)`` or
    ``trainer.step(...)``), nested defs excluded."""
    stack = list(loop.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            f = n.func
            if (isinstance(f, ast.Name) and f.id == "step") or \
                    (isinstance(f, ast.Attribute) and f.attr == "step"):
                return True
        stack.extend(ast.iter_child_nodes(n))
    return False


@rule("eager-in-step-loop", "ast",
      "An un-jitted eager nd.* op dispatched inside a detected "
      "training loop (a loop whose body calls step()): per-step eager "
      "dispatch the compiled step or a bulk scope should absorb -- "
      "each call is a host round trip between device steps.")
def _lint_eager_in_step_loop(tree, path, ctx):
    for node in ast.walk(tree):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        if not _is_train_loop(node):
            continue
        stack = list(node.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.For, ast.While)):
                continue          # inner loops report themselves
            if isinstance(n, ast.Call) and _is_eager_nd_call(n.func):
                yield Diagnostic(
                    "eager-in-step-loop",
                    "eager op %s dispatched inside a training loop; "
                    "move it into the compiled step (TrainStep) or "
                    "wrap the loop in engine.bulk() so the region "
                    "replays as one program"
                    % ".".join(_chain(n.func)),
                    file=path, line=n.lineno)
            stack.extend(ast.iter_child_nodes(n))


# ======================================================================
# Compiled layer: the HLO efficiency auditor
# ======================================================================

AUDIT_SCHEMA = "mxperf.audit.v1"

# advisory thresholds -- shares of the executable's analytic byte
# traffic (transpose/unfused) or of tile-padded MXU bytes (pad waste);
# memory-bound fires when intensity < ridge / factor
THRESHOLDS = {
    "transpose_share": 0.20,
    "unfused_elementwise_share": 0.15,
    "pad_waste": 0.15,
    "membound_ridge_factor": 8.0,
}


def _sublane_for(dtype: str) -> int:
    from ..profiling.hlo import _DTYPE_BYTES
    nbytes = _DTYPE_BYTES.get(dtype, 4)
    if nbytes <= 1:
        return 32
    if nbytes == 2:
        return SUBLANE_BF16
    return SUBLANE_F32


def _tile_pad_bytes(dtype: str, dims) -> int:
    """Bytes of the tile-padded shape: minor dim to 128 lanes, second
    minor to the dtype's sublane count (rank<2 shapes are stored as one
    (sublane, lane) tile row and not charged here)."""
    from ..profiling.hlo import _DTYPE_BYTES
    if len(dims) < 2:
        return _DTYPE_BYTES.get(dtype, 4) * max(1, _prod(dims))
    padded = list(dims)
    padded[-1] = _ceil_to(max(dims[-1], 1), TILE_LANE)
    padded[-2] = _ceil_to(max(dims[-2], 1), _sublane_for(dtype))
    return _DTYPE_BYTES.get(dtype, 4) * _prod(padded)


def _prod(dims):
    out = 1
    for d in dims:
        out *= d
    return out


def audit_hlo_text(text: str) -> Dict:
    """Raw efficiency counters of one compiled module's HLO text.

    Walks the module like ``hlo.analyze`` (fusion call sites carry the
    HBM bytes, while/cond bodies count once) but keeps the numbers the
    advisories need: per-category bytes, the bytes of *top-level*
    elementwise instructions XLA failed to fuse, tile-padding waste on
    the conv/dot operands, and transpose-op provenance.
    """
    from ..profiling import hlo

    entry, comps, _refs = hlo.parse_module(text)
    out = {
        "bytes_total": 0, "flops_total": 0,
        "category_bytes": {c: 0 for c in hlo.CATEGORIES},
        "unfused_elementwise_bytes": 0, "unfused_elementwise_count": 0,
        "transpose_ops": {},          # op_name -> bytes
        "mxu_actual_bytes": 0, "mxu_padded_bytes": 0,
    }

    def fusion_flops(name, seen):
        total = 0
        if name not in comps or name in seen:
            return 0
        seen.add(name)
        for ins in comps[name]:
            if ins.opcode == "fusion":
                for callee in hlo._CALLS_RE.findall(ins.attrs):
                    total += fusion_flops(callee, seen)
                continue
            total += hlo._flops_of(ins)
            _mxu_pad(ins)
        return total

    def fusion_category(name):
        fl = {c: 0 for c in hlo.CATEGORIES}
        n = {c: 0 for c in hlo.CATEGORIES}

        def acc(nm, seen):
            if nm not in comps or nm in seen:
                return
            seen.add(nm)
            for ins in comps[nm]:
                if ins.opcode in hlo._SKIP:
                    continue
                if ins.opcode == "fusion":
                    for callee in hlo._CALLS_RE.findall(ins.attrs):
                        acc(callee, seen)
                    continue
                c = hlo.category_of(ins)
                fl[c] += hlo._flops_of(ins)
                n[c] += 1
        acc(name, set())
        best = max(fl, key=lambda c: fl[c])
        if fl[best] > 0:
            return best
        prio = {"conv_dot": 4, "collective": 3, "transpose_layout": 2,
                "elementwise_fusion": 1, "other": 0}
        return max(hlo.CATEGORIES, key=lambda c: (n[c], prio[c]))

    def _mxu_pad(ins):
        if ins.opcode not in ("convolution", "dot"):
            return
        for dt, dims in list(ins.operand_shapes) + list(ins.out_shapes):
            if len(dims) < 2:
                continue
            from ..profiling.hlo import _DTYPE_BYTES
            actual = _DTYPE_BYTES.get(dt, 4) * _prod(dims)
            out["mxu_actual_bytes"] += actual
            out["mxu_padded_bytes"] += _tile_pad_bytes(dt, dims)

    def walk(name, seen):
        if name not in comps or name in seen:
            return
        seen.add(name)
        for ins in comps[name]:
            op = ins.opcode
            if op in hlo._SKIP:
                continue
            if op == "fusion":
                callees = hlo._CALLS_RE.findall(ins.attrs)
                nbytes = hlo._nbytes(ins.operand_shapes) + \
                    hlo._nbytes(ins.out_shapes)
                cat = fusion_category(callees[0]) if callees \
                    else "elementwise_fusion"
                out["category_bytes"][cat] += nbytes
                out["bytes_total"] += nbytes
                for callee in callees:
                    out["flops_total"] += fusion_flops(callee, seen)
                if cat == "transpose_layout" and ins.op_name:
                    rec = out["transpose_ops"]
                    rec[ins.op_name] = rec.get(ins.op_name, 0) + nbytes
                continue
            if op in ("while", "conditional", "call") or \
                    op.startswith("async-"):
                refs = []
                for rx in (hlo._BODY_RE, hlo._COND_RE, hlo._TRUE_RE,
                           hlo._FALSE_RE, hlo._CALLS_RE, hlo._TOAPPLY_RE):
                    refs.extend(rx.findall(ins.attrs))
                bm = hlo._BRANCHES_RE.search(ins.attrs)
                if bm:
                    refs.extend(n.strip().lstrip("%")
                                for n in bm.group(1).split(","))
                for callee in refs:
                    walk(callee, seen)
                continue
            cat = hlo.category_of(ins)
            nbytes = hlo._nbytes(ins.operand_shapes) + \
                hlo._nbytes(ins.out_shapes)
            out["bytes_total"] += nbytes
            out["category_bytes"][cat] += nbytes
            out["flops_total"] += hlo._flops_of(ins)
            _mxu_pad(ins)
            if cat == "transpose_layout":
                key = ins.op_name or op
                rec = out["transpose_ops"]
                rec[key] = rec.get(key, 0) + nbytes
            elif cat == "elementwise_fusion":
                out["unfused_elementwise_bytes"] += nbytes
                out["unfused_elementwise_count"] += 1

    if entry is not None:
        walk(entry, set())
    return out


def _merge_counters(agg: Dict, cur: Dict):
    for k, v in cur.items():
        if k == "category_bytes":
            for c, b in v.items():
                agg["category_bytes"][c] = \
                    agg["category_bytes"].get(c, 0) + b
        elif k == "transpose_ops":
            for nm, b in v.items():
                agg["transpose_ops"][nm] = \
                    agg["transpose_ops"].get(nm, 0) + b
        else:
            agg[k] = agg.get(k, 0) + v


def _metrics_of(counters: Dict, xla_flops=0.0, xla_bytes=0.0) -> Dict:
    total_b = counters["bytes_total"] or 1
    flops = xla_flops or counters["flops_total"]
    nbytes = xla_bytes or counters["bytes_total"]
    metrics = {
        "transpose_share": round(
            counters["category_bytes"]["transpose_layout"] / total_b, 4),
        "unfused_elementwise_share": round(
            counters["unfused_elementwise_bytes"] / total_b, 4),
        "unfused_elementwise_count":
            counters["unfused_elementwise_count"],
        "pad_waste": round(
            1.0 - counters["mxu_actual_bytes"]
            / counters["mxu_padded_bytes"], 4)
            if counters["mxu_padded_bytes"] else 0.0,
        "intensity": round(flops / nbytes, 4) if nbytes else 0.0,
        "flops": flops,
        "bytes": nbytes,
    }
    return metrics


def _kernel_remedy(kind: str) -> Optional[str]:
    """The registered Pallas kernel remedying an advisory kind
    (docs/kernels.md), e.g. ``unfused-elementwise >= 15% -> candidate
    kernel kernels.fused_bn_relu``.  None when no kernel covers it or
    the kernel tier is unimportable."""
    try:
        from ..kernels import remedy_for
        return remedy_for(kind)
    except Exception:
        return None


def _advisories_for(label: str, metrics: Dict, counters: Dict,
                    ridge: float, thresholds: Dict) -> List[Dict]:
    adv = []
    top_transpose = sorted(counters["transpose_ops"].items(),
                           key=lambda kv: -kv[1])[:3]
    if metrics["transpose_share"] >= thresholds["transpose_share"]:
        adv.append({
            "kind": "transpose-share",
            "category": "transpose_layout",
            "share": metrics["transpose_share"],
            "op_names": [nm for nm, _b in top_transpose],
            "message": "%.0f%% of %r's memory traffic is pure layout "
                       "movement (transpose/copy/pad); top scopes: %s "
                       "-- a channels-last layout or explicit sharding "
                       "usually removes it"
                       % (100 * metrics["transpose_share"], label,
                          ", ".join(nm for nm, _b in top_transpose)
                          or "<unnamed>"),
        })
    if metrics["unfused_elementwise_share"] >= \
            thresholds["unfused_elementwise_share"]:
        adv.append({
            "kind": "unfused-elementwise",
            "category": "elementwise_fusion",
            "share": metrics["unfused_elementwise_share"],
            "op_names": [],
            "message": "%.0f%% of %r's memory traffic is %d elementwise "
                       "instruction(s) XLA left OUTSIDE fusions -- each "
                       "pays a full HBM round trip; check for "
                       "optimization barriers, aliasing, or "
                       "dtype-mismatch breaks in the op chain"
                       % (100 * metrics["unfused_elementwise_share"],
                          label, metrics["unfused_elementwise_count"]),
        })
    if metrics["pad_waste"] >= thresholds["pad_waste"]:
        adv.append({
            "kind": "hlo-pad-waste",
            "category": "conv_dot",
            "share": metrics["pad_waste"],
            "op_names": [],
            "message": "%.0f%% of %r's MXU operand bytes are tile "
                       "padding (shapes vs the (8,128) tile) -- align "
                       "the feature dims (static pad-waste rule names "
                       "the constructors)"
                       % (100 * metrics["pad_waste"], label),
        })
    factor = thresholds["membound_ridge_factor"]
    if metrics["bytes"] and metrics["intensity"] < ridge / factor:
        adv.append({
            "kind": "memory-bound",
            "category": "elementwise_fusion",
            "share": round(min(1.0, metrics["intensity"] / ridge), 4),
            "op_names": [],
            "message": "%r's arithmetic intensity %.2f flops/byte is "
                       ">%.0fx below the device ridge %.1f -- the "
                       "executable is HBM-bound; fuse more work per "
                       "byte (bigger batch, scan K steps, bf16 "
                       "activations)"
                       % (label, metrics["intensity"], factor, ridge),
        })
    for a in adv:
        remedy = _kernel_remedy(a["kind"])
        if remedy:
            a["remedy"] = remedy
    adv.sort(key=lambda a: -a["share"])
    return adv


def perf_audit(thresholds=None, peaks=None) -> Dict:
    """Audit every executable the profiling capture surface registered.

    Lowers each registry entry (hits jax's executable cache), merges
    per-label counters, and returns the audit artifact::

        {"schema": ..., "ridge_intensity": ...,
         "executables": {label: {"metrics": {...},
                                 "advisories": [...]}}}

    ``thresholds`` overrides :data:`THRESHOLDS`; ``peaks`` is an
    optional ``(peak_flops, peak_bytes_per_s)`` pair pinning the ridge
    (tests; CI boxes use the assumed-peaks fallback, recorded in
    ``peaks_assumed``).
    """
    import jax
    from ..profiling import roofline, store

    th = dict(THRESHOLDS)
    if thresholds:
        th.update(thresholds)
    if peaks is not None:
        fl, bw, assumed = peaks[0], peaks[1], False
    else:
        fl, bw, assumed = roofline.device_peaks()
    ridge = fl / bw

    merged: Dict[str, Dict] = {}
    totals: Dict[str, List[float]] = {}
    for label, compiled in store.compiled_executables():
        try:
            text = compiled.as_text()
        except Exception:
            continue
        counters = audit_hlo_text(text)
        xf = xb = 0.0
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            xf = float((ca or {}).get("flops", 0.0))
            xb = float((ca or {}).get("bytes accessed", 0.0))
        except Exception:
            pass
        if label in merged:
            _merge_counters(merged[label], counters)
            totals[label][0] += xf
            totals[label][1] += xb
        else:
            merged[label] = counters
            totals[label] = [xf, xb]

    execs = {}
    for label, counters in merged.items():
        metrics = _metrics_of(counters, *totals[label])
        execs[label] = {
            "metrics": metrics,
            "advisories": _advisories_for(label, metrics, counters,
                                          ridge, th),
        }
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    ranked = sorted(
        (dict(a, executable=label)
         for label, e in execs.items() for a in e["advisories"]),
        key=lambda a: -a["share"])
    return {
        "schema": AUDIT_SCHEMA,
        "backend": backend,
        "ridge_intensity": round(ridge, 3),
        "peaks_assumed": assumed,
        "thresholds": th,
        "executables": execs,
        "advisories": ranked,
    }


def save_audit(path: str, audit=None) -> Dict:
    """Write the current perf audit as JSON (the artifact CI diffs
    against the committed ``ci/perf_baseline.json``)."""
    audit = audit if audit is not None else perf_audit()
    with open(path, "w") as f:
        json.dump(audit, f, indent=1, sort_keys=True)
        f.write("\n")
    return audit


def load_audit(path: str) -> Dict:
    with open(path) as f:
        data = json.load(f)
    if data.get("schema") != AUDIT_SCHEMA:
        raise ValueError("%s is not a %s artifact (schema=%r)"
                         % (path, AUDIT_SCHEMA, data.get("schema")))
    return data


def _audit_tol() -> float:
    try:
        return float(os.environ.get("MXNET_TPU_PERF_AUDIT_TOL", "0.02"))
    except ValueError:
        return 0.02


# share metrics where GROWTH is a regression
_GROWTH_METRICS = ("transpose_share", "unfused_elementwise_share",
                   "pad_waste")


def diff_audit(baseline: Dict, current: Dict,
               tol: Optional[float] = None) -> List[Diagnostic]:
    """Perf drift of ``current`` vs the blessed ``baseline``:

    - an advisory KIND the baseline doesn't carry for that executable
      (or a brand-new executable that audits with advisories) -> error;
    - a share metric (transpose / unfused-elementwise / pad-waste)
      grown more than ``tol`` (absolute; default
      ``MXNET_TPU_PERF_AUDIT_TOL`` = 0.02) -> error;
    - arithmetic intensity dropped >20% -> warning.

    Improvements (smaller shares, fewer advisories) pass silently --
    re-bless with :func:`save_audit` after an intentional change."""
    tol = _audit_tol() if tol is None else tol
    diags: List[Diagnostic] = []
    base_ex = baseline.get("executables", {})
    for label, cur in sorted(current.get("executables", {}).items()):
        base = base_ex.get(label, {"metrics": {}, "advisories": []})
        blessed_kinds = {a["kind"] for a in base.get("advisories", [])}
        for a in cur.get("advisories", []):
            if a["kind"] not in blessed_kinds:
                remedy = a.get("remedy") or _kernel_remedy(a["kind"])
                diags.append(Diagnostic(
                    "perf-drift",
                    "executable %r gained unblessed %r advisory "
                    "(category %s, cost share %.1f%%%s): %s -- fix the "
                    "regression or re-bless via analysis.perf."
                    "save_audit" % (label, a["kind"], a["category"],
                                    100 * a["share"],
                                    ", remedy: %s" % remedy if remedy
                                    else "", a["message"]),
                    node=label))
        bm = base.get("metrics", {})
        cm = cur.get("metrics", {})
        for m in _GROWTH_METRICS:
            b, c = bm.get(m, 0.0), cm.get(m, 0.0)
            if c > b + tol:
                diags.append(Diagnostic(
                    "perf-drift",
                    "executable %r: %s grew %.4f -> %.4f (tolerance "
                    "%.4f); the compiled step got less efficient than "
                    "the baseline blesses" % (label, m, b, c, tol),
                    node=label))
        b_int, c_int = bm.get("intensity", 0.0), cm.get("intensity", 0.0)
        if b_int > 0 and c_int < b_int * 0.8:
            diags.append(Diagnostic(
                "perf-drift",
                "executable %r: arithmetic intensity dropped %.3f -> "
                "%.3f (>20%%); the step is doing less compute per byte "
                "moved" % (label, b_int, c_int),
                node=label, severity=WARNING))
    return diags


@rule("perf-drift", "compiled",
      "A registered executable's efficiency metrics (transpose share, "
      "unfused elementwise bytes, MXU pad waste, intensity) drifted "
      "past the committed ci/perf_baseline.json -- a named, gated "
      "regression instead of a number drifting in BENCH_r0x.  Gate: "
      "mxlint --perf-diff.")
def _rule_perf_drift(baseline, current):
    return diff_audit(baseline, current)
