"""Static graph checker: validate a ``Symbol`` before any device time.

The reference's nnvm passes (InferShape/InferType, graph validation in
``GraphExecutor::Init``) abort the *bind*; this pass runs the same
class of checks standalone -- over ``Symbol._topo()`` with
``jax.eval_shape`` as the oracle -- and reports every problem at once
as :class:`~mxnet_tpu.analysis.core.Diagnostic`s instead of raising on
the first.

Structural rules (no shape info needed):

- ``unknown-op``          op name missing from the registry
- ``dangling-input``      op node with unfilled required tensor slots
- ``duplicate-input``     two distinct variable nodes sharing a name

Shape/dtype rules (need input shapes, given or via ``__shape__`` attrs):

- ``shape-contradiction`` ``jax.eval_shape`` rejects a node whose input
                          shapes are all known
- ``unknown-shape``       a variable's shape cannot be deduced (warning)
- ``dtype-promotion``     a node mixes input dtypes, triggering implicit
                          promotion (warning; fp32 upcasts hiding an
                          intended bf16 path are a classic TPU perf bug)
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

from ..base import MXNetError
from .core import Diagnostic, ERROR, WARNING, rule

__all__ = ["check_symbol", "GraphCheckError", "assert_graph_ok"]


class GraphCheckError(MXNetError):
    """Raised by :func:`assert_graph_ok`; carries the diagnostics."""

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        msg = "graph check failed:\n" + "\n".join(
            d.format() for d in self.diagnostics)
        super().__init__(msg)


# ----------------------------------------------------------------------
# structural rules
# ----------------------------------------------------------------------

@rule("unknown-op", "graph",
      "An op node names an operator missing from the registry; binding "
      "would fail at dispatch time.")
def _check_unknown_op(sym, ctx):
    from ..ops.registry import OP_REGISTRY
    for node in sym._topo():
        if node.op is not None and node.op not in OP_REGISTRY:
            yield Diagnostic("unknown-op",
                             "op %r is not in the registry" % node.op,
                             node=node.name)


@rule("dangling-input", "graph",
      "An op node has fewer inputs than its registered signature "
      "requires (a structurally-required tensor slot is unfilled).")
def _check_dangling_input(sym, ctx):
    from ..ops.registry import OP_REGISTRY
    from ..symbol.symbol import _node_params, _skip_auto_var
    for node in sym._topo():
        op = OP_REGISTRY.get(node.op) if node.op is not None else None
        if op is None or op.variadic:
            continue
        params = _node_params(node, op)
        required = [a for a in op.arg_names
                    if not _skip_auto_var(node.op, params, a)]
        if len(node.inputs) < len(required):
            missing = required[len(node.inputs):]
            yield Diagnostic(
                "dangling-input",
                "op %s(%s) is missing tensor input(s) %r"
                % (node.op, node.name, missing), node=node.name)


@rule("duplicate-input", "graph",
      "Two distinct variable nodes share one name, so a single feed "
      "entry silently binds both.")
def _check_duplicate_input(sym, ctx):
    seen: Dict[str, int] = {}
    for node in sym._topo():
        if node.op is not None:
            continue
        if node.name in seen:
            yield Diagnostic(
                "duplicate-input",
                "variable name %r is used by %d distinct input nodes; "
                "binding by name is ambiguous"
                % (node.name, seen[node.name] + 1), node=node.name)
        seen[node.name] = seen.get(node.name, 0) + 1


# ----------------------------------------------------------------------
# shape/dtype walk (forward abstract interpretation, error-collecting
# twin of symbol._infer_shapes_forward)
# ----------------------------------------------------------------------

def _shape_walk(sym, known):
    """Yield diagnostics; shares the per-op deduction rules with
    ``infer_shape`` so the checker and the binder can never disagree."""
    import jax
    import numpy as np

    from ..ops.registry import OP_REGISTRY
    from ..symbol.symbol import (_node_params, _param_shape_rule,
                                 _parse_attr_value)

    known = {k: tuple(v) for k, v in (known or {}).items()}
    specs = {}                       # (id(node), oi) -> ShapeDtypeStruct
    reported_unknown = set()

    def report_unknown(name):
        if name not in reported_unknown:
            reported_unknown.add(name)
            yield Diagnostic(
                "unknown-shape",
                "shape of input %r cannot be deduced; pass it to the "
                "checker or annotate the variable" % name,
                node=name, severity=WARNING)

    for node in sym._topo():
        if node.op is None:
            if node.name in known:
                shape = known[node.name]
            elif "__shape__" in node.attrs:
                shape = tuple(_parse_attr_value(node.attrs["__shape__"]))
            else:
                continue
            if any(not isinstance(d, int) or d <= 0 for d in shape):
                # deferred-init shape (0 = unknown dim, e.g. a conv
                # weight before in_channels is seen): leave it to the
                # per-op deduction rule at the consumer
                continue
            dt = np.dtype(str(node.attrs.get("__dtype__", "float32")))
            specs[(id(node), 0)] = jax.ShapeDtypeStruct(shape, dt)
            continue
        op = OP_REGISTRY.get(node.op)
        if op is None:
            continue                 # unknown-op already reported
        params = _node_params(node, op)
        in_shapes = [specs.get((id(src), oi)) for src, oi in node.inputs]
        in_shapes = [tuple(s.shape) if s is not None else None
                     for s in in_shapes]
        in_specs = []
        unresolved = False
        for i, (src, oi) in enumerate(node.inputs):
            s = specs.get((id(src), oi))
            if s is None and src.op is None:
                arg = op.arg_names[i] if i < len(op.arg_names) else ""
                shape = _param_shape_rule(node.op, params, arg, in_shapes)
                if shape is not None:
                    s = jax.ShapeDtypeStruct(shape, np.float32)
                    specs[(id(src), oi)] = s
            if s is None:
                if src.op is None:
                    yield from report_unknown(src.name)
                unresolved = True
            in_specs.append(s)
        if unresolved:
            continue
        in_dtypes = {str(s.dtype) for s in in_specs}
        if len(in_dtypes) > 1:
            yield Diagnostic(
                "dtype-promotion",
                "op %s(%s) mixes input dtypes %s; the result is "
                "implicitly promoted" % (node.op, node.name,
                                         sorted(in_dtypes)),
                node=node.name, severity=WARNING)
        pad = 0
        if not op.variadic and len(in_specs) < len(op.arg_names):
            pad = len(op.arg_names) - len(in_specs)
        fn = op.fcompute
        if op.stateful_rng:
            fn = functools.partial(fn, jax.random.PRNGKey(0))
        if any(p.name == "training" for p in op.params) and \
                "training" not in node.attrs:
            params["training"] = False
        try:
            out = jax.eval_shape(
                lambda *a: fn(*(list(a) + [None] * pad), **params),
                *in_specs)
        except Exception as e:
            yield Diagnostic(
                "shape-contradiction",
                "op %s(%s) rejects input shapes %s: %s"
                % (node.op, node.name,
                   [tuple(s.shape) for s in in_specs], e),
                node=node.name)
            continue
        if isinstance(out, (tuple, list)):
            for i, o in enumerate(out):
                specs[(id(node), i)] = o
        else:
            specs[(id(node), 0)] = out


@rule("shape-contradiction", "graph",
      "Forward shape propagation (jax.eval_shape over the op's compute "
      "function) rejects a node whose input shapes are all known.")
def _check_shapes(sym, ctx):
    for d in _shape_walk(sym, (ctx or {}).get("shapes")):
        if d.rule == "shape-contradiction":
            yield d


@rule("unknown-shape", "graph",
      "A variable's shape is neither given nor deducible, leaving part "
      "of the graph unvalidated.", severity=WARNING)
def _check_unknown_shape(sym, ctx):
    for d in _shape_walk(sym, (ctx or {}).get("shapes")):
        if d.rule == "unknown-shape":
            yield d


@rule("dtype-promotion", "graph",
      "A node mixes input dtypes; implicit promotion can silently "
      "upcast a reduced-precision path to fp32.", severity=WARNING)
def _check_dtype_promotion(sym, ctx):
    for d in _shape_walk(sym, (ctx or {}).get("shapes")):
        if d.rule == "dtype-promotion":
            yield d


# ----------------------------------------------------------------------
# driver
# ----------------------------------------------------------------------

_STRUCTURAL = ("unknown-op", "dangling-input", "duplicate-input")


def check_symbol(sym, shapes: Optional[Dict[str, tuple]] = None,
                 structural_only: bool = False,
                 ignore=()) -> List[Diagnostic]:
    """Run every graph rule over ``sym``; returns all diagnostics.

    ``shapes`` maps input names to shapes (like ``infer_shape`` kwargs).
    ``structural_only`` skips the shape walk (cheap enough for a bind
    gate even on large graphs).  ``ignore`` drops the listed rule ids.
    """
    from .core import RULES
    diags: List[Diagnostic] = []
    for rid in _STRUCTURAL:
        if rid in ignore:
            continue
        diags.extend(RULES[rid].check(sym, None))
    if not structural_only:
        # one walk, routed by rule id (the per-rule wrappers exist for
        # --list-rules discoverability; the driver avoids 3x the work)
        for d in _shape_walk(sym, shapes):
            if d.rule not in ignore:
                diags.append(d)
    return diags


def assert_graph_ok(sym, shapes=None, structural_only=False, ignore=()):
    """Raise :class:`GraphCheckError` when any error-severity diagnostic
    fires -- the opt-in bind gate used by ``Executor``."""
    diags = [d for d in check_symbol(sym, shapes, structural_only, ignore)
             if d.severity == ERROR]
    if diags:
        raise GraphCheckError(diags)
    return True
