"""Pluggable rule framework shared by the three analysis passes.

The reference validates a graph only when binding it (``GraphExecutor``
runs nnvm InferShape/InferType and aborts on the first inconsistency);
everything else -- host syncs inside what will become a compiled region,
params that silently force recompilation -- surfaces as a runtime
failure or a perf cliff.  Here every check is a ``Rule`` with a stable
id, a severity, and one of three kinds:

- ``graph``: walks a ``Symbol`` (``mxnet_tpu.analysis.graph_check``)
- ``ast``:   walks a source file's AST (``mxnet_tpu.analysis.trace_lint``)
- ``registry``: cross-references op specs with engine internals
  (``mxnet_tpu.analysis.retrace``)

Later PRs add a rule by decorating a checker with ``@rule(...)``; the
CLI, the CI gate, suppression comments, and ``--json`` output all pick
it up with no further wiring.
"""
from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

__all__ = ["Diagnostic", "Rule", "RULES", "rule", "get_rule", "list_rules",
           "filter_suppressed", "render_human", "render_json",
           "ERROR", "WARNING"]

ERROR = "error"
WARNING = "warning"


@dataclass
class Diagnostic:
    """One finding: where, which rule, and what to do about it."""
    rule: str
    message: str
    file: Optional[str] = None
    line: Optional[int] = None
    node: Optional[str] = None       # graph node name for graph rules
    severity: str = ERROR

    @property
    def location(self) -> str:
        if self.file is not None:
            return "%s:%s" % (self.file, self.line if self.line else "?")
        if self.node is not None:
            return "graph:%s" % self.node
        return "<registry>"

    def format(self) -> str:
        return "%s: %s[%s]: %s" % (self.location, self.severity,
                                   self.rule, self.message)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "message": self.message, "file": self.file,
                "line": self.line, "node": self.node}


@dataclass
class Rule:
    """A registered check.  ``check``'s signature depends on ``kind``:

    - ast:      ``check(tree, path, ctx) -> Iterable[Diagnostic]``
    - graph:    ``check(symbol, ctx) -> Iterable[Diagnostic]``
    - registry: ``check(ctx) -> Iterable[Diagnostic]``
    """
    id: str
    kind: str                 # "ast" | "graph" | "registry"
    doc: str
    severity: str = ERROR
    check: Callable = field(default=None, repr=False)


RULES: Dict[str, Rule] = {}


def rule(id: str, kind: str, doc: str, severity: str = ERROR):
    """Decorator registering a checker under a stable rule id."""
    def deco(fn: Callable) -> Callable:
        if id in RULES:
            raise ValueError("duplicate analysis rule id: %s" % id)
        RULES[id] = Rule(id=id, kind=kind, doc=doc, severity=severity,
                         check=fn)
        return fn
    return deco


def get_rule(id: str) -> Rule:
    return RULES[id]


def list_rules(kind: Optional[str] = None) -> List[Rule]:
    return [r for r in RULES.values() if kind is None or r.kind == kind]


# -- per-line suppression ----------------------------------------------
# ``# mxlint: disable=rule-a,rule-b`` silences those rules on its line;
# ``# mxlint: disable`` with no list silences every rule on the line.
_SUPPRESS_RE = re.compile(r"#\s*mxlint:\s*disable(?:=([\w,\-]+))?")


def suppressions_for_line(line_text: str) -> Optional[set]:
    """None if no directive; empty set means 'all rules'."""
    m = _SUPPRESS_RE.search(line_text)
    if m is None:
        return None
    return set(filter(None, (m.group(1) or "").split(",")))


def filter_suppressed(diags: List[Diagnostic],
                      source_lines: List[str]) -> List[Diagnostic]:
    """Drop file diagnostics whose source line carries a matching
    ``# mxlint: disable`` directive."""
    out = []
    for d in diags:
        if d.line is not None and 1 <= d.line <= len(source_lines):
            sup = suppressions_for_line(source_lines[d.line - 1])
            if sup is not None and (not sup or d.rule in sup):
                continue
        out.append(d)
    return out


# -- output ------------------------------------------------------------

def render_human(diags: List[Diagnostic]) -> str:
    lines = [d.format() for d in diags]
    errors = sum(d.severity == ERROR for d in diags)
    warnings = len(diags) - errors
    lines.append("mxlint: %d error(s), %d warning(s)" % (errors, warnings))
    return "\n".join(lines)


def render_json(diags: List[Diagnostic]) -> str:
    errors = sum(d.severity == ERROR for d in diags)
    return json.dumps({
        "diagnostics": [d.to_dict() for d in diags],
        "errors": errors,
        "warnings": len(diags) - errors,
    }, indent=2)
