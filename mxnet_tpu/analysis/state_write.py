"""Atomicity lint: bare ``open(..., "wb")`` state writes (ISSUE 3).

Before the checkpoint subsystem, five save paths wrote state with bare
``open()`` -- a SIGKILL mid-write (the normal end of a TPU preemption
grace window) left a truncated file that *loads garbage or crashes the
resume*.  They now all route through ``mx.checkpoint.core``'s atomic
tmp+fsync+``os.replace`` commit; this rule keeps it that way.

A diagnostic fires for ``open(<path>, "wb"/"bw"/"wb+"/...)`` inside any
function whose name marks it as a state-serialization path (``save``,
``checkpoint``, ``states``, ``dump``, ``export`` in the name) -- except
inside ``checkpoint/core.py`` itself, which owns the staging files.
Serialization *primitives* that legitimately write a caller-staged path
(``ndarray.save``) carry a ``# mxlint: disable=bare-state-write``
with a comment pointing callers at ``checkpoint.core.commit``.
"""
from __future__ import annotations

import ast
import re

from .core import Diagnostic, rule

__all__ = []

# function names that mark a state-serialization path
_STATE_FN_RE = re.compile(
    r"(save|checkpoint|states|dump|serialize|export)", re.IGNORECASE)
# the module allowed to open staging files directly
_EXEMPT_PATH_RE = re.compile(r"checkpoint[/\\]core\.py$")


def _write_binary_mode(call):
    """The mode string of an ``open`` call, if it is a binary write."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and "w" in mode.value and "b" in mode.value:
        return mode.value
    return None


@rule("bare-state-write", "ast",
      "A bare open(..., 'wb') in a save/checkpoint/export path writes "
      "state without torn-write protection; route it through "
      "mxnet_tpu.checkpoint.core (commit / atomic_write_bytes).")
def _lint_bare_state_write(tree, path, ctx):
    if _EXEMPT_PATH_RE.search(path or ""):
        return
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _STATE_FN_RE.search(fn.name):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Name) and f.id == "open"):
                continue
            mode = _write_binary_mode(node)
            if mode is None:
                continue
            yield Diagnostic(
                "bare-state-write",
                "open(..., %r) inside %r writes state without "
                "torn-write protection: a kill mid-write leaves a "
                "truncated file that loads garbage.  Use "
                "checkpoint.core.atomic_write_bytes / commit "
                "(tmp+fsync+os.replace)" % (mode, fn.name),
                file=path, line=node.lineno)
