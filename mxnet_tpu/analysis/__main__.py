"""``python -m mxnet_tpu.analysis`` -> the mxlint CLI."""
import sys

from .cli import main

sys.exit(main())
