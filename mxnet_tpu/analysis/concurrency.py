"""Concurrency-safety AST pass (ISSUE 5 static half).

PRs 2-4 made the package genuinely multi-threaded; this pass makes the
resulting lock discipline machine-checked, the way ``trace_lint`` made
trace safety machine-checked.  It

- **inventories** every lock/condition/event/queue the package creates
  (``threading.*`` or the sanitized ``mxnet_tpu.sync`` factories).  A
  ``sync.Lock(name="telemetry.registry")`` creation adopts the literal
  name, so the static graph and the runtime sanitizer
  (``mxnet_tpu/sync.py``) reason about the SAME identities; unnamed
  primitives get a structural ``file:Class.attr`` identity;
- builds a **lock-acquisition-order graph** from lexically nested
  ``with lock:`` scopes across the whole linted tree and reports every
  cycle as ``lock-order-inversion``;
- checks four per-file thread-discipline rules:
  ``unguarded-shared-write``, ``blocking-under-lock``, ``bare-thread``
  and ``sleep-poll`` (table in docs/analysis.md).

Suppress a finding with ``# mxlint: disable=<rule>`` on its line; the
runtime closure of the order graph is ``MXNET_TPU_TSAN=1``
(docs/concurrency.md).
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Diagnostic, filter_suppressed, rule

__all__ = ["FileInventory", "inventory_file", "order_edges",
           "static_order_edges", "audit_lock_order", "find_cycles"]

# primitive constructors, by the role they play in the order graph
_ORDERED_CTORS = {"Lock", "RLock", "Condition"}   # participate in ordering
_EVENT_CTORS = {"Event"}
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
_THREAD_CTORS = {"Thread"}
# module aliases the package uses for primitives
_SYNC_MODULES = {"threading", "_threading", "sync", "_sync", "queue"}

# blocking calls flagged under a held lock (rule blocking-under-lock)
_BLOCKING_METHODS = {"wait", "wait_for", "join", "get", "put",
                     "asnumpy", "wait_to_read", "device_get"}
_BLOCKING_FUNCS = {"open", "waitall", "device_get", "sleep"}


def _ctor_of(call: ast.Call) -> Optional[Tuple[str, str]]:
    """``(kind, ctor_name)`` when ``call`` constructs a sync primitive:
    kind is ``lock``/``event``/``queue``/``thread``."""
    f = call.func
    name = None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in _SYNC_MODULES:
        name = f.attr
    elif isinstance(f, ast.Name):
        # `from threading import Lock` style -- only unambiguous names
        if f.id in ("RLock", "Condition"):
            name = f.id
    if name is None:
        return None
    if name in _ORDERED_CTORS:
        return ("lock", name)
    if name in _EVENT_CTORS:
        return ("event", name)
    if name in _QUEUE_CTORS:
        return ("queue", name)
    if name in _THREAD_CTORS:
        return ("thread", name)
    return None


def _name_kwarg(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


class FileInventory:
    """Per-file table of sync primitives and where they bind.

    ``attrs[cls][attr] -> (kind, lock_id, ctor, line)`` for
    ``self.X = ctor()`` bindings; ``globals_``/``locals_`` likewise for
    module-level and function-local bindings (locals keyed by
    ``(funcname, varname)``)."""

    def __init__(self, path: str):
        self.path = path
        self.attrs: Dict[str, Dict[str, tuple]] = {}
        self.globals_: Dict[str, tuple] = {}
        self.locals_: Dict[Tuple[str, str], tuple] = {}

    def _short(self):
        p = Path(self.path)
        return "/".join(p.parts[-2:]) if len(p.parts) >= 2 else p.name

    def record(self, cls, fn, target, call):
        ctor = _ctor_of(call)
        if ctor is None:
            return
        kind, ctor_name = ctor
        explicit = _name_kwarg(call)
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self" and cls:
            lock_id = explicit or "%s:%s.%s" % (self._short(), cls,
                                                target.attr)
            self.attrs.setdefault(cls, {})[target.attr] = \
                (kind, lock_id, ctor_name, call.lineno)
        elif isinstance(target, ast.Name):
            if fn is None:
                lock_id = explicit or "%s:%s" % (self._short(), target.id)
                self.globals_[target.id] = (kind, lock_id, ctor_name,
                                            call.lineno)
            else:
                lock_id = explicit or "%s:%s.%s" % (self._short(), fn,
                                                    target.id)
                self.locals_[(fn, target.id)] = (kind, lock_id, ctor_name,
                                                 call.lineno)

    def resolve(self, cls, fn, expr) -> Optional[tuple]:
        """Inventory entry a ``with``-expression / call target names."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls:
            return self.attrs.get(cls, {}).get(expr.attr)
        if isinstance(expr, ast.Name):
            if fn is not None and (fn, expr.id) in self.locals_:
                return self.locals_[(fn, expr.id)]
            return self.globals_.get(expr.id)
        return None

    def primitives(self) -> List[tuple]:
        out = list(self.globals_.values())
        out.extend(v for attrs in self.attrs.values()
                   for v in attrs.values())
        out.extend(self.locals_.values())
        return out


class _InventoryVisitor(ast.NodeVisitor):
    def __init__(self, inv: FileInventory):
        self.inv = inv
        self.cls = None
        self.fn = None

    def visit_ClassDef(self, node):
        prev, self.cls = self.cls, node.name
        prev_fn, self.fn = self.fn, None
        self.generic_visit(node)
        self.cls, self.fn = prev, prev_fn

    def visit_FunctionDef(self, node):
        prev, self.fn = self.fn, node.name
        self.generic_visit(node)
        self.fn = prev

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        if isinstance(node.value, ast.Call):
            for tgt in node.targets:
                self.inv.record(self.cls, self.fn, tgt, node.value)
        self.generic_visit(node)


def inventory_file(tree, path: str) -> FileInventory:
    inv = FileInventory(path)
    _InventoryVisitor(inv).visit(tree)
    return inv


# ----------------------------------------------------------------------
# acquisition-order edges from nested `with` scopes
# ----------------------------------------------------------------------

class _FunctionScopeWalker(ast.NodeVisitor):
    """Walks one file function-by-function, maintaining the lexical
    stack of held (inventoried) locks, and calling ``on_with``/
    ``on_call`` hooks.  Nested function definitions get a fresh held
    stack (they run on their own schedule -- usually another thread)."""

    def __init__(self, inv: FileInventory):
        self.inv = inv
        self.cls = None
        self.fn = None
        self.held: List[tuple] = []     # (lock_id, kind, with_expr, line)

    # hooks --------------------------------------------------------
    def on_with(self, lock_id, kind, node):
        pass

    def on_call(self, node):
        pass

    # scope tracking -----------------------------------------------
    def visit_ClassDef(self, node):
        prev_cls, prev_fn = self.cls, self.fn
        self.cls, self.fn = node.name, None
        self.generic_visit(node)
        self.cls, self.fn = prev_cls, prev_fn

    def visit_FunctionDef(self, node):
        prev_fn, prev_held = self.fn, self.held
        self.fn, self.held = node.name, []
        for stmt in node.body:
            self.visit(stmt)
        self.fn, self.held = prev_fn, prev_held

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def visit_With(self, node):
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            entry = self.inv.resolve(self.cls, self.fn, expr)
            if entry is not None and entry[0] == "lock":
                kind = entry[0]
                self.on_with(entry[1], kind, node)
                self.held.append((entry[1], kind, expr, node.lineno))
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        self.on_call(node)
        self.generic_visit(node)


class _EdgeCollector(_FunctionScopeWalker):
    def __init__(self, inv):
        super().__init__(inv)
        self.edges: List[tuple] = []    # (outer_id, inner_id, path, line)

    def on_with(self, lock_id, kind, node):
        if self.held:
            outer = self.held[-1][0]
            if outer != lock_id:
                self.edges.append((outer, lock_id, self.inv.path,
                                   node.lineno))


def order_edges(tree, path) -> List[tuple]:
    """``(outer, inner, file, line)`` acquisition-order edges of one
    file's lexically nested ``with lock:`` scopes."""
    col = _EdgeCollector(inventory_file(tree, path))
    col.visit(tree)
    return col.edges


def _parse_tree(paths) -> Iterable[Tuple[str, ast.AST, List[str]]]:
    for path in paths:
        p = Path(path)
        if not p.exists():
            continue
        files = sorted(p.glob("**/*.py")) if p.is_dir() else [p]
        for f in files:
            try:
                src = f.read_text()
                yield str(f), ast.parse(src, str(f)), src.splitlines()
            except (OSError, SyntaxError):
                continue


def static_order_edges(paths) -> Set[Tuple[str, str]]:
    """The package-wide acquisition-order edge set -- what
    ``mxnet_tpu.sync.seed_static_order`` folds into the runtime graph."""
    edges = set()
    for path, tree, _src in _parse_tree(paths):
        edges.update((a, b) for a, b, _f, _l in order_edges(tree, path))
    return edges


def find_cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles (as node lists) via SCC decomposition --
    every SCC with more than one node, plus self-loops."""
    index = {}
    low = {}
    on_stack = set()
    stack: List[str] = []
    sccs = []
    counter = [0]
    nodes = set(edges)
    for succs in edges.values():
        nodes.update(succs)

    def strongconnect(v):
        # iterative Tarjan (package files can nest deep)
        work = [(v, iter(sorted(edges.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1 or node in edges.get(node, ()):
                    sccs.append(sorted(scc))

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    return sccs


def audit_lock_order(paths, ignore=(), report_files=None
                     ) -> List[Diagnostic]:
    """Cross-file half of the pass: build the global acquisition-order
    graph over ``paths`` and report each cycle at every edge site
    inside it.  ``report_files`` (a set of path strings) restricts
    *reporting* -- not graph construction -- for ``--changed`` runs."""
    if "lock-order-inversion" in ignore:
        return []
    graph: Dict[str, Set[str]] = {}
    sites: Dict[tuple, List[tuple]] = {}   # (a, b) -> [(file, line, lines)]
    for path, tree, src_lines in _parse_tree(paths):
        for a, b, f, line in order_edges(tree, path):
            graph.setdefault(a, set()).add(b)
            sites.setdefault((a, b), []).append((f, line, src_lines))
    diags = []
    for cyc in find_cycles(graph):
        members = set(cyc)
        order = " -> ".join(cyc + [cyc[0]])
        for (a, b), where in sorted(sites.items()):
            if a in members and b in members and b in graph.get(a, ()):
                for f, line, src_lines in where:
                    if report_files is not None and f not in report_files:
                        continue
                    d = Diagnostic(
                        "lock-order-inversion",
                        "acquiring %r while holding %r closes the lock "
                        "cycle [%s]; two threads taking it from "
                        "different entry points deadlock.  Pick one "
                        "global order (docs/concurrency.md) or drop "
                        "one nesting" % (b, a, order),
                        file=f, line=line)
                    if not filter_suppressed([d], src_lines):
                        continue
                    diags.append(d)
    return diags


@rule("lock-order-inversion", "project",
      "Nested `with lock:` scopes across the tree form a cycle in the "
      "acquisition-order graph -- an A/B-B/A deadlock waiting for the "
      "right schedule.  Runtime closure: MXNET_TPU_TSAN=1.")
def _lint_lock_order(paths, ctx):
    return audit_lock_order(paths)


# ----------------------------------------------------------------------
# per-file rules
# ----------------------------------------------------------------------

def _thread_target_names(tree) -> Set[str]:
    """Names of functions/methods passed as ``target=`` to a Thread
    constructor anywhere in the file."""
    targets = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        ctor = _ctor_of(node)
        if ctor is None or ctor[0] != "thread":
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Name):
                targets.add(v.id)
            elif isinstance(v, ast.Attribute):
                targets.add(v.attr)
    return targets


def _is_guarded(stack_of_withs) -> bool:
    return bool(stack_of_withs)


class _SharedWriteVisitor(ast.NodeVisitor):
    """Collects ``self.X`` writes per class, split into thread-body
    writes and main-path writes, each tagged guarded/unguarded.
    ``__init__``/``_start``-time writes before the thread exists are
    construction, not sharing -- ``__init__`` is exempt."""

    def __init__(self, inv: FileInventory, thread_targets: Set[str]):
        self.inv = inv
        self.thread_targets = thread_targets
        self.cls = None
        self.fn_stack: List[str] = []
        self.with_depth = 0              # inventoried-lock withs held
        # {cls: {attr: {"thread": [(line, guarded)],
        #               "main": [(line, guarded)]}}}
        self.writes: Dict[str, Dict[str, Dict[str, list]]] = {}

    def _in_thread_body(self):
        return any(fn in self.thread_targets for fn in self.fn_stack)

    def visit_ClassDef(self, node):
        prev, self.cls = self.cls, node.name
        self.generic_visit(node)
        self.cls = prev

    def visit_FunctionDef(self, node):
        self.fn_stack.append(node.name)
        prev_depth, self.with_depth = self.with_depth, 0
        self.generic_visit(node)
        self.with_depth = prev_depth
        self.fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        fn = self.fn_stack[-1] if self.fn_stack else None
        locked = 0
        for item in node.items:
            entry = self.inv.resolve(self.cls, fn, item.context_expr)
            if entry is not None and entry[0] == "lock":
                locked += 1
        self.with_depth += locked
        for stmt in node.body:
            self.visit(stmt)
        self.with_depth -= locked

    visit_AsyncWith = visit_With

    def _record_write(self, attr_node, line):
        if self.cls is None or not self.fn_stack:
            return
        if self.fn_stack[0] == "__init__":
            return                       # happens-before thread start
        # writes to the sync primitives themselves are lifecycle, not data
        entry = self.inv.attrs.get(self.cls, {}).get(attr_node.attr)
        if entry is not None:
            return
        side = "thread" if self._in_thread_body() else "main"
        rec = self.writes.setdefault(self.cls, {}).setdefault(
            attr_node.attr, {"thread": [], "main": []})
        rec[side].append((line, self.with_depth > 0))

    def _maybe_record(self, target, line):
        if isinstance(target, ast.Subscript):
            # `self.X[...] = v` mutates the shared container X
            target = target.value
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            self._record_write(target, line)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._maybe_record(elt, line)

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._maybe_record(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._maybe_record(node.target, node.lineno)
        self.generic_visit(node)


@rule("unguarded-shared-write", "ast",
      "An attribute written both inside a Thread(target=...) body and "
      "outside it with at least one side holding no inventoried lock; "
      "the interleaving is a data race.")
def _lint_unguarded_shared_write(tree, path, ctx):
    thread_targets = _thread_target_names(tree)
    if not thread_targets:
        return
    inv = inventory_file(tree, path)
    v = _SharedWriteVisitor(inv, thread_targets)
    v.visit(tree)
    for cls, attrs in sorted(v.writes.items()):
        for attr, rec in sorted(attrs.items()):
            if not rec["thread"] or not rec["main"]:
                continue
            unguarded = [(ln, "thread") for ln, g in rec["thread"]
                         if not g]
            unguarded += [(ln, "main") for ln, g in rec["main"] if not g]
            if not unguarded:
                continue
            line, side = unguarded[0]
            yield Diagnostic(
                "unguarded-shared-write",
                "self.%s is written both inside a thread body and on "
                "the %s path, and this write holds no lock; guard both "
                "sides with one mxnet_tpu.sync lock or hand the value "
                "through a queue" % (attr,
                                     "main" if side == "thread"
                                     else "calling"),
                file=path, line=line)


class _BlockingVisitor(_FunctionScopeWalker):
    """Flags blocking calls made while an inventoried lock is
    lexically held.  ``c.wait()`` where ``c`` is the lock's own
    condition object (the with-context itself) is the condition idiom
    and exempt."""

    def __init__(self, inv):
        super().__init__(inv)
        self.diags: List[Diagnostic] = []

    def _call_name(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            return f.attr, f.value
        if isinstance(f, ast.Name):
            return f.id, None
        return None, None

    def on_call(self, node):
        if not self.held:
            return
        name, recv = self._call_name(node)
        if name is None:
            return
        blocking = None
        if recv is None:
            if name in _BLOCKING_FUNCS:
                blocking = "%s()" % name
        else:
            if name in ("wait", "wait_for"):
                # `with cond: cond.wait()` is the condition protocol;
                # waiting on a DIFFERENT primitive while holding is not
                held_expr = self.held[-1][2]
                if ast.dump(recv) == ast.dump(held_expr):
                    return
                blocking = ".%s()" % name
            elif name in ("get", "put"):
                entry = self.inv.resolve(self.cls, self.fn, recv)
                if entry is not None and entry[0] == "queue":
                    blocking = "queue.%s()" % name
            elif name == "join":
                entry = self.inv.resolve(self.cls, self.fn, recv)
                if entry is not None and entry[0] == "thread":
                    blocking = "Thread.join()"
            elif name in ("asnumpy", "wait_to_read", "device_get",
                          "waitall"):
                blocking = ".%s()" % name
            elif name == "sleep" and isinstance(recv, ast.Name) \
                    and recv.id == "time":
                blocking = "time.sleep()"
        if blocking is None and recv is None and name == "open":
            blocking = "open()"
        if blocking is not None:
            lock_id = self.held[-1][0]
            self.diags.append(Diagnostic(
                "blocking-under-lock",
                "%s while holding %r; every other thread needing that "
                "lock stalls behind this call (and a cyclic wait "
                "deadlocks).  Move the blocking call outside the "
                "critical section or hand off through a queue"
                % (blocking, lock_id),
                file=self.inv.path, line=node.lineno))


@rule("blocking-under-lock", "ast",
      "A blocking call (queue get/put, join, wait, device_get/asnumpy/"
      "waitall, open, time.sleep) made while an inventoried lock is "
      "held serializes -- or deadlocks -- every contender.")
def _lint_blocking_under_lock(tree, path, ctx):
    v = _BlockingVisitor(inventory_file(tree, path))
    v.visit(tree)
    yield from v.diags


def _daemonized_before_start(fn_node, var):
    """True when ``var.daemon = True`` appears in ``fn_node``."""
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and tgt.attr == "daemon" \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == var \
                        and isinstance(node.value, ast.Constant) \
                        and node.value.value is True:
                    return True
    return False


@rule("bare-thread", "ast",
      "threading.Thread created without daemon=True (the established "
      "pattern: daemon thread + join on close/reset + errors captured "
      "and re-raised at the consumer).  A non-daemon worker wedges "
      "interpreter shutdown when its consumer dies first.")
def _lint_bare_thread(tree, path, ctx):
    # map each Thread(...) call to its enclosing function for the
    # `t.daemon = True` escape hatch
    class V(ast.NodeVisitor):
        def __init__(self):
            self.fn = None
            self.found = []           # (call, enclosing_fn, assigned_var)

        def visit_FunctionDef(self, node):
            prev, self.fn = self.fn, node
            self.generic_visit(node)
            self.fn = prev

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Assign(self, node):
            if isinstance(node.value, ast.Call):
                ctor = _ctor_of(node.value)
                if ctor is not None and ctor[0] == "thread":
                    var = node.targets[0].id \
                        if isinstance(node.targets[0], ast.Name) else None
                    self.found.append((node.value, self.fn, var))
                    return
            self.generic_visit(node)

        def visit_Call(self, node):
            ctor = _ctor_of(node)
            if ctor is not None and ctor[0] == "thread":
                self.found.append((node, self.fn, None))
            self.generic_visit(node)

    v = V()
    v.visit(tree)
    seen = set()
    for call, fn, var in v.found:
        if id(call) in seen:
            continue
        seen.add(id(call))
        daemon_kw = any(kw.arg == "daemon"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in call.keywords)
        if daemon_kw:
            continue
        if var and fn is not None and _daemonized_before_start(fn, var):
            continue
        yield Diagnostic(
            "bare-thread",
            "threading.Thread without daemon=True; follow the package "
            "pattern (daemon worker + join in close()/reset() + errors "
            "captured and re-raised at the consumer) or the thread "
            "outlives its consumer and wedges shutdown",
            file=path, line=call.lineno)


@rule("sleep-poll", "ast",
      "time.sleep inside a while loop is a polling loop: it burns "
      "latency when the condition flips early and CPU when it never "
      "does.  Wait on an Event/Condition with a timeout instead.")
def _lint_sleep_poll(tree, path, ctx):
    class V(ast.NodeVisitor):
        def __init__(self):
            self.loops = 0
            self.hits = []

        def visit_While(self, node):
            self.loops += 1
            self.generic_visit(node)
            self.loops -= 1

        def visit_FunctionDef(self, node):
            prev, self.loops = self.loops, 0
            self.generic_visit(node)
            self.loops = prev

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            f = node.func
            if self.loops and isinstance(f, ast.Attribute) \
                    and f.attr == "sleep" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "time":
                self.hits.append(node)
            self.generic_visit(node)

    v = V()
    v.visit(tree)
    for node in v.hits:
        yield Diagnostic(
            "sleep-poll",
            "time.sleep in a while loop polls; wait on the state "
            "change itself (sync.Event.wait(timeout) / "
            "Condition.wait_for) so the loop wakes the moment the "
            "condition flips",
            file=path, line=node.lineno)
