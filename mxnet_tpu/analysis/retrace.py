"""Retrace auditor: flag op params that defeat the compile caches.

Two compile caches key execution, and op params sit differently in each:

- The **eager** per-op cache (``ndarray/ndarray.py``) keys on
  ``(op, shapes, dtypes, params, amp)`` but threads the names in
  ``_DYNAMIC_PARAMS`` as *traced* scalars, so a per-step learning rate
  does not recompile.
- The **hybridize** cache (``gluon/block.py :: _CACHE_KEY_STATIC``)
  keys on ``(training, amp-policy, shapes, dtypes)`` only; op params
  are baked into the trace as compile-time constants.

An op param whose name marks it as per-step-varying (a schedule, a
step counter, a loss scale) that is NOT in the eager dynamic set is an
unbounded-recompilation hazard: every distinct value compiles a fresh
XLA executable.  The seed had exactly one -- ``lamb_update_phase1``'s
``t`` recompiled LAMB on every step until it joined ``_DYNAMIC_PARAMS``.

Rules:

- ``retrace-hazard``  (warning) varying-named op param outside the
  eager dynamic set
- ``cache-key-drift`` (warning) the cache-key anchors this audit reads
  (``_CACHE_KEY_STATIC``, ``_DYNAMIC_PARAMS``) are gone or no longer
  cover what the audit assumes -- the engine changed; update the audit
"""
from __future__ import annotations

import ast
import inspect
from typing import List

from .core import Diagnostic, WARNING, rule

__all__ = ["audit_retrace", "cache_key_fields", "eager_dynamic_params",
           "VARYING_PARAM_NAMES"]

# Param names that, by convention in this registry, carry per-step
# values (optimizer schedules, step counters, loss scaling).  Constant
# hyperparameters with trace-time control flow (``clip_gradient``) and
# shape-like params (``step`` strides) are deliberately excluded.
VARYING_PARAM_NAMES = {
    "lr", "wd", "rescale_grad", "scalar", "t", "loss_scale", "num_update",
}


def eager_dynamic_params() -> frozenset:
    """The eager engine's dynamically-threaded param names."""
    from ..ndarray import ndarray as nd_impl
    return getattr(nd_impl, "_DYNAMIC_PARAMS", frozenset())


def cache_key_fields() -> List[str]:
    """Static fields of the hybridize compiled-entry cache key, from
    ``gluon/block.py`` (empty list if the anchor is unparseable)."""
    from ..gluon import block as block_mod
    static = getattr(block_mod, "_CACHE_KEY_STATIC", None)
    if static is not None:
        return list(static)
    # fallback: recover the key tuple from the source (pre-constant
    # versions of block.py)
    try:
        src = inspect.getsource(block_mod.HybridBlock._call_cached)
        tree = ast.parse("if 1:\n" + src)
    except (OSError, SyntaxError, TypeError):
        return []
    fields: List[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "key"
                for t in node.targets):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    fields.append(sub.id)
                elif isinstance(sub, ast.Attribute):
                    fields.append(sub.attr)
    return fields


@rule("retrace-hazard", "registry",
      "An op param carries a per-step-varying value that is a trace-"
      "time constant in every compile cache; each distinct value "
      "forces an XLA recompile.", severity=WARNING)
def _audit_varying_params(ctx):
    from ..ops.registry import OP_REGISTRY
    dynamic = eager_dynamic_params()
    seen = set()
    for _, op in sorted(OP_REGISTRY.items()):
        if id(op) in seen:           # aliases share the Op object
            continue
        seen.add(id(op))
        hazards = [p.name for p in op.params
                   if p.name in VARYING_PARAM_NAMES and p.name not in dynamic]
        if hazards:
            yield Diagnostic(
                "retrace-hazard",
                "op %r params %r vary per step but are static in both "
                "compile caches (eager _DYNAMIC_PARAMS and the "
                "hybridize key %s); each distinct value recompiles -- "
                "add them to _DYNAMIC_PARAMS or thread them as tensor "
                "inputs" % (op.name, hazards, cache_key_fields()),
                node=op.name, severity=WARNING)


@rule("cache-key-drift", "registry",
      "The compile-cache key anchors this audit reads no longer match "
      "what it expects; update the audit with the engine.",
      severity=WARNING)
def _audit_cache_key(ctx):
    fields = cache_key_fields()
    expected = {"training", "shape", "dtype"}
    missing = expected - set(fields)
    if not fields or missing:
        yield Diagnostic(
            "cache-key-drift",
            "could not confirm hybridize cache-key fields %s in "
            "gluon/block.py (found %s); the retrace audit may be stale"
            % (sorted(expected), sorted(set(fields))),
            severity=WARNING)
    if not eager_dynamic_params():
        yield Diagnostic(
            "cache-key-drift",
            "ndarray._DYNAMIC_PARAMS is missing or empty; the eager "
            "per-op cache no longer threads per-step params and the "
            "retrace audit may be stale", severity=WARNING)


def audit_retrace() -> List[Diagnostic]:
    """Run every registry-kind rule; imports the op modules first so
    the registry is fully populated."""
    import mxnet_tpu.ops  # noqa: F401  (populates OP_REGISTRY)
    from .core import RULES
    diags: List[Diagnostic] = []
    for r in RULES.values():
        if r.kind != "registry":
            continue
        for d in r.check(None):
            d.severity = r.severity
            diags.append(d)
    return diags
