"""``mxnet_tpu.analysis``: static graph checker + trace-safety linter
+ retrace auditor behind one pluggable rule framework.

The reference validates graphs only at bind time and has no notion of
jit-breaking Python; this subsystem catches both classes before any
device time is spent (docs/analysis.md):

- :func:`check_symbol` / :func:`assert_graph_ok` -- validate a
  ``Symbol`` (shapes, dtypes, dangling/duplicate inputs, unknown ops).
  Also available as an opt-in bind gate: ``Executor(..., check=True)``
  or ``MXNET_TPU_GRAPH_CHECK=1``.
- :func:`lint_paths` -- AST-lint source trees for trace-unsafe Python
  (host syncs and value branches in compiled scopes, mutable defaults,
  bare ``except:``) and for non-atomic state writes (bare
  ``open(..., "wb")`` in save paths outside ``checkpoint/core.py`` --
  the ISSUE 3 torn-write guard).
- :func:`audit_retrace` -- cross-reference op param specs with the
  compile-cache keys to flag unbounded-recompilation hazards.
- :func:`audit_lock_order` / the concurrency rules -- inventory every
  lock/condition/event, build the acquisition-order graph from nested
  ``with lock:`` scopes (cycle => ``lock-order-inversion``), and check
  thread discipline (``unguarded-shared-write``, ``blocking-under-lock``,
  ``bare-thread``, ``sleep-poll``).  Runtime closure:
  ``MXNET_TPU_TSAN=1`` (``mxnet_tpu.sync``, docs/concurrency.md).
- :func:`audit_sharding` / the sharding sanitizer (docs/sharding.md) --
  SPMD spec linting (``mesh-axis-unknown``, ``shard-map-spec-arity``,
  ``implicit-reshard``), the donation audit (``undonated-train-state``,
  ``donated-reuse``), and the compiled layer:
  :func:`collective_contract`/:func:`diff_contract` extract GSPMD
  collective counts/bytes per registered executable and gate them
  against the committed ``ci/sharding_baseline.json``
  (``collective-drift``); :func:`transfer_guard` makes silent in-step
  host transfers raise.
- the numerics sanitizer (docs/numerics.md) -- five static
  dtype-hazard rules (``bf16-sensitive-reduce``, ``unscaled-half-loss``,
  ``half-optimizer-state``, ``implicit-downcast``,
  ``nonfinite-guard-missing``), the compiled precision audit
  :func:`numerics_audit` gated against ``ci/numerics_baseline.json``
  (``numerics-drift``, ``mxlint --numerics-diff``), and the runtime
  non-finite sentinel (:func:`finite_sentinel`,
  ``MXNET_TPU_NUMERICS_CHECK=1``) raising typed
  :class:`NonFiniteError` with first-offender attribution.
- the memory-pressure sanitizer / hbmlint (docs/memory.md) -- five
  static HBM-hazard rules (``device-ref-accumulation``,
  ``unbounded-shape-cache``, ``host-materialize-large``,
  ``retained-temp-across-step``, ``feed-depth-unbounded``), the
  compiled peak-HBM audit :func:`memory_audit` gated against
  ``ci/memory_baseline.json`` (``memory-drift``,
  ``mxlint --memory-diff``) with :func:`hbm_plan` batch-bucket
  extrapolation, and the runtime live-buffer leak sentinel
  (``MXNET_TPU_MEMORY_WATCH=1``) over ``jax.live_arrays()``.
  ``mxlint --sarif`` exports every pass's findings as SARIF 2.1.0.

CLI: ``python -m mxnet_tpu.analysis`` (or the ``mxlint`` entry point);
``ci/run_all.sh lint`` runs it with ``--self``.  Add a rule with
``@mxnet_tpu.analysis.rule(...)``.
"""
from .core import (Diagnostic, Rule, RULES, rule, get_rule, list_rules,
                   render_human, render_json, ERROR, WARNING)
from .graph_check import GraphCheckError, assert_graph_ok, check_symbol
from .trace_lint import lint_file, lint_paths, lint_source
from . import state_write  # noqa: F401  (registers bare-state-write)
from .concurrency import audit_lock_order, static_order_edges
from .retrace import audit_retrace
from .sharding import (audit_sharding, collective_contract,
                       collective_profile, diff_contract, load_contract,
                       save_contract, transfer_guard)
from .perf import (audit_hlo_text, diff_audit, load_audit, perf_audit,
                   save_audit)
# numerics shares perf's save/load/diff_audit spelling; reach them as
# analysis.numerics.save_audit etc.
from . import numerics
from .numerics import (NonFiniteError, finite_sentinel, finite_tree,
                       numerics_audit)
# memory shares the save/load/diff_audit spelling too; reach them as
# analysis.memory.save_audit etc.
from . import memory
from .memory import hbm_plan, memory_audit
from . import sarif
from .sarif import to_sarif, write_sarif
from .cli import main

__all__ = [
    "Diagnostic", "Rule", "RULES", "rule", "get_rule", "list_rules",
    "render_human", "render_json", "ERROR", "WARNING",
    "GraphCheckError", "assert_graph_ok", "check_symbol",
    "lint_file", "lint_paths", "lint_source",
    "audit_lock_order", "static_order_edges", "audit_retrace",
    "audit_sharding", "collective_contract", "collective_profile",
    "diff_contract", "load_contract", "save_contract", "transfer_guard",
    "audit_hlo_text", "diff_audit", "load_audit", "perf_audit",
    "save_audit",
    "numerics", "NonFiniteError", "finite_sentinel", "finite_tree",
    "numerics_audit",
    "memory", "hbm_plan", "memory_audit",
    "sarif", "to_sarif", "write_sarif",
    "main",
]
