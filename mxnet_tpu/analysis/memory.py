"""hbmlint (ISSUE 20 tentpole): memory-pressure sanitizer.

HBM is the resource that actually caps batch size and serving
footprint, and every way of wasting it fails *silently*: a list that
keeps device references alive grows until an OOM ten thousand steps in,
an unbounded shape-keyed cache leaks one executable per novel shape,
and a step that retains its previous output doubles peak HBM without a
single error.  This pass guards all three layers, in the same shape as
the sharding sanitizer (PR 7), perflint (PR 10) and mxnumerics
(PR 16): static AST rules + a compiled audit + a runtime sentinel.

**Static layer** (AST, under the PR-1 rule framework; runs in
``mxlint --self``):

- ``device-ref-accumulation``: appending device arrays/NDArrays to a
  container inside a training/step loop -- the classic HBM leak: every
  retained reference pins a device buffer, so ``losses.append(loss)``
  keeps one activation set alive per step.
- ``unbounded-shape-cache``: a module/class-level dict cache keyed on
  shape/dtype with no LRU bound or eviction -- the PR-8 Predictor bug
  pattern (one compiled program pinned per novel input shape) as a
  rule.
- ``host-materialize-large``: ``asnumpy``/``device_get`` of a tensor
  whose static shape exceeds a threshold inside a loop body -- a
  many-MB host copy per iteration.
- ``retained-temp-across-step``: a jit output bound to ``self.X`` in a
  step loop without donation or an explicit delete -- the previous
  step's output stays live through the next dispatch, doubling the
  state footprint.
- ``feed-depth-unbounded``: a queue/deque staging device arrays
  constructed without ``maxlen``/``maxsize`` -- a producer that runs
  ahead of the consumer stages unbounded device batches.

**Compiled layer**: :func:`memory_audit` walks PR 6's persistent
``profiling.store`` registry and reads each executable's XLA
``memory_analysis()``: argument/output/temp/alias/peak-HBM bytes, a
temp-share advisory (temp > k x args => remat/fusion remedy naming the
dominant HLO category) and an alias-coverage advisory (donatable
step-shaped args not aliased, cross-referencing PR 7's donation
rules).  ``save_audit``/``load_audit``/``diff_audit`` (schema
``mxmemory.audit.v1``) + the committed ``ci/memory_baseline.json``
gate drift exactly like perflint/mxnumerics: ``mxlint --memory-diff
BASE CUR`` errors on an unblessed executable or peak HBM grown past
``MXNET_TPU_MEMORY_AUDIT_TOL``, passes on shrinkage (rule
``memory-drift``; CI stage ``memlint``; docs/memory.md).
:func:`hbm_plan` extrapolates peak HBM across batch buckets (linear in
batch-carried bytes, constant in params -- a two-point secant over two
real compiles) to answer "largest bucket that fits"; serving bucket
validation and ``bench_batch_hbm_sweep`` both drive it.

**Runtime layer**: the live-buffer leak sentinel.  Behind
``MXNET_TPU_MEMORY_WATCH=1`` (one module-flag check when off),
:func:`live_census` buckets ``jax.live_arrays()`` by shape/dtype and
publishes the ``memory.live_bytes``/``memory.live_arrays`` gauges;
``ContinuousTrainer`` ticks a :class:`LeakSentinel` per step, which
closes a census window every goodput-window boundary and flags
monotonic live-bytes growth (EWMA+MAD, the PR-14 machinery) naming the
top-growing shape bucket -- publish-guard aware, so a checkpoint
snapshot spike never flags.  The ``memory.leak`` chaos fail point
(action :func:`pin_action`) pins arrays in a hidden list so the
sentinel must catch a real leak; ``/statusz`` carries a ``memory``
row.
"""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional

from .core import Diagnostic, rule
from .perf import _chain, _is_train_loop, _own_loops
from .sharding import (_call_name, _file_defs_and_assigns, _has_donation,
                       _is_jit_call)

__all__ = [
    "AUDIT_SCHEMA", "THRESHOLDS",
    "executable_memory", "memory_audit", "save_audit", "load_audit",
    "diff_audit", "hbm_plan", "device_hbm_bytes",
    "watch_enabled", "live_census", "LeakSentinel", "sentinel",
    "pin_action", "pinned_count", "unpin_all", "status_row",
    "reset_watch",
]


def _fmt_bytes(v) -> str:
    """Human bytes -- same rendering as mxprof (profiling.cli)."""
    for unit, div in (("GB", 1e9), ("MB", 1e6), ("kB", 1e3)):
        if v >= div:
            return "%.2f %s" % (v / div, unit)
    return "%d B" % v


# ----------------------------------------------------------------------
# shared AST helpers
# ----------------------------------------------------------------------

# chains rooted here produce device arrays (nd.zeros, jnp.square,
# jax.device_put); np.* is HOST and deliberately absent
_DEVICE_ROOTS = {"nd", "jnp", "jax"}

# a call through one of these leaves lands the value host-side -- the
# blessed way to record a per-step scalar without pinning the buffer
_HOST_ESCAPES = {"float", "int", "bool", "str", "item", "asnumpy",
                 "asscalar", "tolist", "device_get", "asarray"}

# callables whose result is (conservatively) a device value: the step
# fn itself, forward passes, loss computation
_MODEL_CALL_RE = re.compile(r"(step|forward|loss|net|model|block)", re.I)


def _is_host_escape(expr) -> bool:
    """Does ``expr`` materialize its value host-side (float(loss),
    loss.item(), x.asnumpy(), jax.device_get(x))?"""
    if not isinstance(expr, ast.Call):
        return False
    parts = _chain(expr.func)
    return bool(parts) and parts[-1] in _HOST_ESCAPES


def _is_device_producing(expr) -> bool:
    """Conservatively: does ``expr`` produce a device array -- an
    nd/jnp/jax chain call, or a model/step/loss-shaped call?"""
    if not isinstance(expr, ast.Call):
        return False
    if _is_host_escape(expr):
        return False
    parts = _chain(expr.func)
    if not parts:
        return False
    if parts[0] in _DEVICE_ROOTS:
        return True
    if _MODEL_CALL_RE.search(parts[-1]):
        # ...unless an argument already escaped to host
        return True
    # method call on a device-producing receiver: loss.mean()
    if isinstance(expr.func, ast.Attribute) and \
            _is_device_producing(expr.func.value):
        return True
    return False


def _loop_body_walk(loop):
    """Statements/expressions lexically in a loop body, nested defs and
    inner loops excluded (inner loops report themselves)."""
    stack = list(loop.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.For, ast.While)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _loop_device_taints(loop) -> set:
    """Names assigned a device value inside the loop body -- the
    references whose retention pins a buffer per iteration."""
    tainted = set()
    for _ in range(2):          # two passes: forward-flowing reuse
        for n in _loop_body_walk(loop):
            if not isinstance(n, (ast.Assign, ast.AugAssign)):
                continue
            value = n.value
            hot = _is_device_producing(value) or (
                isinstance(value, ast.Name) and value.id in tainted) or (
                isinstance(value, ast.Attribute)
                and isinstance(value.value, ast.Name)
                and value.value.id in tainted)
            if not hot:
                continue
            targets = n.targets if isinstance(n, ast.Assign) \
                else [n.target]
            for tgt in targets:
                for t in ast.walk(tgt):
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
    return tainted


def _is_device_ref(expr, tainted) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, (ast.Tuple, ast.List)):
        return any(_is_device_ref(e, tainted) for e in expr.elts)
    if isinstance(expr, ast.Attribute):
        return _is_device_ref(expr.value, tainted)
    return _is_device_producing(expr)


# ----------------------------------------------------------------------
# device-ref-accumulation
# ----------------------------------------------------------------------

@rule("device-ref-accumulation", "ast",
      "A device array/NDArray appended to a container inside a "
      "training loop: every retained reference pins its device buffer, "
      "so the list grows one activation set per step -- the classic "
      "slow HBM leak an OOM ten thousand steps in is made of.  Append "
      "a host scalar (float(loss), loss.item()) or bound the "
      "container (collections.deque(maxlen=N)).")
def _lint_device_ref_accumulation(tree, path, ctx):
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        if not _is_train_loop(loop):
            continue
        tainted = _loop_device_taints(loop)
        for n in _loop_body_walk(loop):
            hot = None
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("append", "extend", "appendleft") \
                    and n.args:
                # deque(maxlen=...) is the blessed bounded form, but a
                # deque is not resolvable here; flag only list-ish
                # receivers (a Name/attribute) -- the sweep's fixtures
                # cover both polarities
                if _is_device_ref(n.args[0], tainted):
                    hot = n
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.op, ast.Add) and \
                    isinstance(n.value, (ast.List, ast.Tuple)) and \
                    any(_is_device_ref(e, tainted)
                        for e in n.value.elts):
                hot = n
            if hot is None:
                continue
            yield Diagnostic(
                "device-ref-accumulation",
                "device array accumulated into a container inside a "
                "training loop (line %d): each retained reference "
                "pins a device buffer, growing HBM one entry per "
                "step.  Did you mean to append a host scalar "
                "(float(x) / x.item() / x.asnumpy()) or use "
                "collections.deque(maxlen=N)?" % hot.lineno,
                file=path, line=hot.lineno)


# ----------------------------------------------------------------------
# unbounded-shape-cache
# ----------------------------------------------------------------------

_SHAPE_ATTR_RE = re.compile(r"^(shape|dtype|aval|ndim)$")
_SHAPE_NAME_RE = re.compile(r"shape|dtype|sig|aval|fingerprint", re.I)


def _mentions_shape(expr, depth=0) -> bool:
    """Does the key expression spell shape/dtype (``x.shape``,
    ``str(a.dtype)``, a name like ``sig``/``shape_key``)?"""
    if expr is None or depth > 6:
        return False
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and _SHAPE_ATTR_RE.match(n.attr):
            return True
        if isinstance(n, ast.Name) and _SHAPE_NAME_RE.search(n.id):
            return True
    return False


def _module_and_class_dicts(tree) -> Dict[str, int]:
    """Names bound to a fresh dict at module or class level -- the
    long-lived caches whose growth nothing bounds."""
    out = {}
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, ast.ClassDef)]
    for scope in scopes:
        for node in scope.body:
            tgt = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                tgt, value = node.target.id, node.value
            if tgt is None or value is None:
                continue
            if isinstance(value, ast.Dict) and not value.keys:
                out[tgt] = node.lineno
            elif isinstance(value, ast.Call) and \
                    _call_name(value) == "dict" and not value.args \
                    and not value.keywords:
                out[tgt] = node.lineno
    return out


def _eviction_evidence(tree, name) -> bool:
    """Anything in the file that bounds ``name``: pop/popitem/del, a
    ``len(name)`` comparison (an explicit bound check), or an LRU
    move_to_end."""
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in ("pop", "popitem", "move_to_end") and \
                isinstance(n.func.value, ast.Name) and \
                n.func.value.id == name:
            return True
        if isinstance(n, ast.Delete):
            for t in n.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == name:
                    return True
        if isinstance(n, ast.Compare):
            for side in [n.left] + list(n.comparators):
                if isinstance(side, ast.Call) and \
                        _call_name(side) == "len" and side.args and \
                        isinstance(side.args[0], ast.Name) and \
                        side.args[0].id == name:
                    return True
    return False


@rule("unbounded-shape-cache", "ast",
      "A module/class-level dict cache keyed on shape/dtype with no "
      "LRU bound or eviction anywhere in the file: every novel input "
      "shape pins another compiled program / device buffer forever -- "
      "the Predictor bug pattern (PR 8).  Bound it (pop the oldest "
      "past N entries, like MXNET_TPU_SERVING_PREDICTOR_CACHE) or "
      "suppress with the invariant that bounds the key space.")
def _lint_unbounded_shape_cache(tree, path, ctx):
    caches = _module_and_class_dicts(tree)
    if not caches:
        return
    defs, _assigns = _file_defs_and_assigns(tree)
    # per-function name -> latest assigned value, for resolving a key
    # precomputed as `key = (x.shape, x.dtype)` two lines above
    reported = set()
    for fn in [tree] + list(defs.values()):
        local = {}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                local[n.targets[0].id] = n.value
        for n in ast.walk(fn):
            name = key = None
            if isinstance(n, ast.Assign):
                for tgt in n.targets:
                    if isinstance(tgt, ast.Subscript) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id in caches:
                        name, key = tgt.value.id, tgt.slice
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "setdefault" and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id in caches and n.args:
                name, key = n.func.value.id, n.args[0]
            if name is None or (name, path) in reported:
                continue
            shapey = _mentions_shape(key)
            if not shapey and isinstance(key, ast.Name) and \
                    key.id in local:
                shapey = _mentions_shape(local[key.id])
            if not shapey:
                continue
            if _eviction_evidence(tree, name):
                continue
            reported.add((name, path))
            yield Diagnostic(
                "unbounded-shape-cache",
                "dict cache %r is keyed on shape/dtype but nothing in "
                "this file ever evicts from it: every novel shape "
                "pins another entry (compiled program / device "
                "buffer) forever.  Did you mean an LRU bound "
                "(pop the oldest past N entries) or an explicit "
                "invariant suppression?" % name,
                file=path, line=n.lineno)


# ----------------------------------------------------------------------
# host-materialize-large
# ----------------------------------------------------------------------

_CREATOR_LEAVES = {"zeros", "ones", "full", "empty", "uniform",
                   "normal", "array"}
_MATERIALIZE_LEAVES = {"asnumpy", "device_get"}


def _literal_elems(node) -> Optional[int]:
    """Element count a literal shape spells, None when not static."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        total = 1
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            total *= e.value
        return total
    return None


def _static_shapes(scope) -> Dict[str, int]:
    """Name -> static element count for arrays created with a literal
    shape in ``scope`` (``x = nd.zeros((4096, 4096))``)."""
    out = {}
    for n in ast.walk(scope):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n is not scope:
            continue
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Call)):
            continue
        parts = _chain(n.value.func)
        if not parts or parts[-1] not in _CREATOR_LEAVES:
            continue
        shape_node = n.value.args[0] if n.value.args else None
        for kw in n.value.keywords:
            if kw.arg == "shape":
                shape_node = kw.value
        elems = _literal_elems(shape_node)
        if elems is not None:
            out[n.targets[0].id] = elems
    return out


@rule("host-materialize-large", "ast",
      "asnumpy()/device_get() of a statically-large tensor inside a "
      "loop body: each iteration synchronously copies the whole "
      "buffer to host -- many MB per step of D2H traffic stalling the "
      "dispatch pipeline.  Materialize once outside the loop, or "
      "reduce on device first (x.sum().asnumpy() ships 4 bytes).")
def _lint_host_materialize_large(tree, path, ctx):
    threshold = THRESHOLDS["host_materialize_elems"]
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        shapes = _static_shapes(scope)
        if not shapes:
            continue
        loops = _own_loops(scope) if not isinstance(scope, ast.Module) \
            else (n for n in scope.body if isinstance(n, (ast.For,
                                                          ast.While)))
        for loop in loops:
            for n in _loop_body_walk(loop):
                if not isinstance(n, ast.Call):
                    continue
                parts = _chain(n.func)
                if not parts or parts[-1] not in _MATERIALIZE_LEAVES:
                    continue
                if parts[-1] == "asnumpy":
                    src = n.func.value \
                        if isinstance(n.func, ast.Attribute) else None
                else:
                    src = n.args[0] if n.args else None
                if not isinstance(src, ast.Name):
                    continue
                elems = shapes.get(src.id)
                if elems is None or elems <= threshold:
                    continue
                yield Diagnostic(
                    "host-materialize-large",
                    "%s of %r (%s elements, statically known) inside "
                    "a loop body: a full synchronous D2H copy per "
                    "iteration.  Did you mean to materialize once "
                    "outside the loop, or reduce on device first?"
                    % (parts[-1], src.id, "{:,}".format(elems)),
                    file=path, line=n.lineno)


# ----------------------------------------------------------------------
# retained-temp-across-step
# ----------------------------------------------------------------------

def _jit_assign_calls(tree) -> Dict[str, ast.Call]:
    """Name -> the jax.jit(...) call it is bound to, anywhere in the
    file (``step = jax.jit(body, ...)``)."""
    out = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name) and \
                isinstance(n.value, ast.Call) and _is_jit_call(n.value):
            out[n.targets[0].id] = n.value
    return out


@rule("retained-temp-across-step", "ast",
      "A jit output bound to self.X inside a training loop with "
      "neither donation on the jit nor an explicit delete: the "
      "PREVIOUS step's output buffer stays live while the next "
      "dispatch allocates a new one -- steady-state HBM carries two "
      "copies of the state.  Donate the state argnums "
      "(donate_argnums=...) or `del self.X` before the call.")
def _lint_retained_temp_across_step(tree, path, ctx):
    jits = _jit_assign_calls(tree)
    if not jits:
        return
    # each loop is judged exactly once, under its INNERMOST enclosing
    # function -- that is where donation evidence for the jit lives
    loop_scopes = {}

    def _map(node, fn):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.For, ast.While)):
                loop_scopes[child] = fn
            inner = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn
            _map(child, inner)

    _map(tree, None)
    for loop, enclosing in loop_scopes.items():
        if not _is_train_loop(loop):
            continue
        # `del self.X` / `self.X = None` inside the loop releases
        # the previous buffer before the next dispatch
        released = set()
        for n in _loop_body_walk(loop):
            if isinstance(n, ast.Delete):
                for t in n.targets:
                    if isinstance(t, ast.Attribute):
                        released.add(t.attr)
            if isinstance(n, ast.Assign) and \
                    isinstance(n.value, ast.Constant) and \
                    n.value.value is None:
                for tgt in n.targets:
                    if isinstance(tgt, ast.Attribute):
                        released.add(tgt.attr)
        for n in _loop_body_walk(loop):
            if not (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Call)):
                continue
            fname = _call_name(n.value)
            jit_call = jits.get(fname)
            if jit_call is None:
                continue
            if _has_donation(jit_call, enclosing):
                continue
            for tgt in n.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and \
                        tgt.attr not in released:
                    yield Diagnostic(
                        "retained-temp-across-step",
                        "jit output of %r bound to self.%s in a "
                        "training loop without donation or an "
                        "explicit delete: the previous step's "
                        "buffer stays live through the next "
                        "dispatch.  Did you mean donate_argnums= "
                        "on the jit, or `del self.%s` before the "
                        "call?" % (fname, tgt.attr, tgt.attr),
                        file=path, line=n.lineno)


# ----------------------------------------------------------------------
# feed-depth-unbounded
# ----------------------------------------------------------------------

_FEED_NAME_RE = re.compile(r"feed|queue|stag|prefetch|pin|inflight",
                           re.I)


def _unbounded_queue_ctor(call: ast.Call) -> Optional[str]:
    """``'deque'``/``'Queue'`` when the constructor has no depth bound,
    None otherwise."""
    parts = _chain(call.func)
    if not parts:
        return None
    leaf = parts[-1]
    if leaf == "deque":
        if len(call.args) >= 2:
            return None                      # deque(iterable, maxlen)
        for kw in call.keywords:
            if kw.arg == "maxlen" and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None):
                return None
        return "deque"
    if leaf in ("Queue", "LifoQueue", "SimpleQueue"):
        if leaf == "SimpleQueue":
            return "SimpleQueue"             # never bounded
        bound = None
        if call.args:
            bound = call.args[0]
        for kw in call.keywords:
            if kw.arg == "maxsize":
                bound = kw.value
        if bound is None or (isinstance(bound, ast.Constant)
                             and bound.value in (0, None)):
            return leaf
        return None
    return None


def _depth_bound_evidence(tree, name) -> bool:
    """A ``len(q)`` comparison anywhere in the file bounds the queue as
    surely as a ctor maxlen -- the shed-on-full pattern
    (``if len(self._queue) >= self.max_queue: raise``)."""
    def _is_target(x):
        return (isinstance(x, ast.Name) and x.id == name) or \
            (isinstance(x, ast.Attribute) and x.attr == name)
    for n in ast.walk(tree):
        if not isinstance(n, ast.Compare):
            continue
        for side in [n.left] + list(n.comparators):
            if isinstance(side, ast.Call) and \
                    _call_name(side) == "len" and side.args and \
                    _is_target(side.args[0]):
                return True
    return False


def _stages_device_arrays(scope, target) -> bool:
    """Does ``scope`` put device-producing values into ``target``
    (``q.put(device_put(batch))``, ``feed.append(nd.array(...))``)?"""
    for n in ast.walk(scope):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("put", "put_nowait", "append",
                                    "appendleft")
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == target and n.args):
            continue
        for a in ast.walk(n.args[0]):
            if isinstance(a, ast.Call):
                parts = _chain(a.func)
                if parts and (parts[0] in _DEVICE_ROOTS
                              or parts[-1] == "device_put"):
                    return True
    return False


@rule("feed-depth-unbounded", "ast",
      "A queue/deque staging device arrays constructed without a "
      "maxlen/maxsize depth bound: a producer that outruns the "
      "consumer stages unbounded device batches -- HBM grows with the "
      "producer lead instead of the double-buffering depth.  Bound it "
      "(deque(maxlen=N) / Queue(maxsize=N), cf. "
      "MXNET_TPU_FEED_DEPTH).")
def _lint_feed_depth_unbounded(tree, path, ctx):
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))]
    seen = set()
    for scope in scopes:
        body = scope.body
        for node in body if isinstance(scope, ast.ClassDef) else \
                ast.walk(scope):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            kind = _unbounded_queue_ctor(node.value)
            if kind is None or node.lineno in seen:
                continue
            tgt = node.targets[0]
            name = tgt.id if isinstance(tgt, ast.Name) else (
                tgt.attr if isinstance(tgt, ast.Attribute) else None)
            if name is None:
                continue
            staging = bool(_FEED_NAME_RE.search(name)) or \
                _stages_device_arrays(scope, name)
            if not staging:
                continue
            if _depth_bound_evidence(tree, name):
                continue
            seen.add(node.lineno)
            yield Diagnostic(
                "feed-depth-unbounded",
                "%s %r stages device batches without a depth bound: "
                "a producer lead becomes unbounded staged HBM.  Did "
                "you mean %s (cf. MXNET_TPU_FEED_DEPTH's default of "
                "2 = double buffering)?"
                % (kind, name,
                   "deque(maxlen=N)" if kind == "deque"
                   else "Queue(maxsize=N)"),
                file=path, line=node.lineno)


# ======================================================================
# Compiled layer: the peak-HBM auditor
# ======================================================================

AUDIT_SCHEMA = "mxmemory.audit.v1"

THRESHOLDS = {
    # temp-share advisory fires when temp bytes exceed this multiple of
    # the argument bytes (rematerialization/fusion headroom)
    "temp_args_factor": 2.0,
    # alias-coverage advisory fires when aliased bytes cover less than
    # this share of the donatable (output-shaped) argument bytes
    "alias_cover_min": 0.5,
    # static host-materialize-large threshold (elements)
    "host_materialize_elems": 1 << 20,
}


def executable_memory(compiled) -> Dict:
    """One executable's XLA memory analysis as plain ints -- the same
    numbers profiling.cost records, with the same zeroed fallback when
    the backend offers no analysis."""
    try:
        ms = compiled.memory_analysis()
        arg = int(getattr(ms, "argument_size_in_bytes", 0) or 0)
        out = int(getattr(ms, "output_size_in_bytes", 0) or 0)
        tmp = int(getattr(ms, "temp_size_in_bytes", 0) or 0)
        alias = int(getattr(ms, "alias_size_in_bytes", 0) or 0)
    except Exception:
        arg = out = tmp = alias = 0
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "alias_bytes": alias,
        "peak_hbm_bytes": max(0, arg + out + tmp - alias),
    }


def _leaf_nbytes(leaf) -> int:
    try:
        import numpy as np
        n = 1
        for d in leaf.shape:
            n *= int(d)
        return n * np.dtype(leaf.dtype).itemsize
    except Exception:
        return 0


def _donatable_bytes(args, lowered) -> int:
    """Bytes of argument leaves whose (shape, dtype) matches an output
    leaf -- the step-shaped state PR 7's donation rules want donated.
    0 when output info is unavailable."""
    import jax
    try:
        outs = jax.tree_util.tree_leaves(lowered.out_info)
    except Exception:
        return 0
    remaining: Dict[tuple, int] = {}
    for o in outs:
        try:
            key = (tuple(o.shape), str(o.dtype))
        except Exception:
            continue
        remaining[key] = remaining.get(key, 0) + 1
    total = 0
    for leaf in jax.tree_util.tree_leaves(args):
        if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
            continue
        key = (tuple(leaf.shape), str(leaf.dtype))
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            total += _leaf_nbytes(leaf)
    return total


def _dominant_category(compiled) -> Optional[str]:
    """The HLO category carrying the most bytes in this executable --
    what a remat/fusion remedy should aim at (perf.audit_hlo_text)."""
    try:
        from .perf import audit_hlo_text
        counters = audit_hlo_text(compiled.as_text())
        cats = {c: b for c, b in counters["category_bytes"].items() if b}
        if not cats:
            return None
        return max(cats, key=lambda c: cats[c])
    except Exception:
        return None


def _metrics_of(mem: Dict) -> Dict:
    args = mem["argument_bytes"] or 1
    donatable = mem["donatable_bytes"]
    return {
        "argument_bytes": mem["argument_bytes"],
        "output_bytes": mem["output_bytes"],
        "temp_bytes": mem["temp_bytes"],
        "alias_bytes": mem["alias_bytes"],
        "donatable_bytes": donatable,
        "peak_hbm_bytes": mem["peak_hbm_bytes"],
        "temp_share": round(mem["temp_bytes"] / args, 4),
        "alias_coverage": round(mem["alias_bytes"] / donatable, 4)
        if donatable else 1.0,
    }


def _advisories_for(label: str, metrics: Dict, dominant: Optional[str],
                    thresholds: Dict) -> List[Dict]:
    adv = []
    if metrics["argument_bytes"] and metrics["temp_bytes"] > \
            thresholds["temp_args_factor"] * metrics["argument_bytes"]:
        adv.append({
            "kind": "temp-share",
            "share": metrics["temp_share"],
            "dominant_category": dominant,
            "message": "%r's temp allocations are %.1fx its argument "
                       "bytes (%s temp vs %s args; dominant HLO "
                       "category: %s): the live intermediate set "
                       "dominates peak HBM -- rematerialize "
                       "(jax.checkpoint) the %s region or let fusion "
                       "shrink the live range"
                       % (label, metrics["temp_share"],
                          _fmt_bytes(metrics["temp_bytes"]),
                          _fmt_bytes(metrics["argument_bytes"]),
                          dominant or "<unknown>",
                          dominant or "dominant"),
        })
    donatable = metrics["donatable_bytes"]
    if donatable and metrics["alias_coverage"] < \
            thresholds["alias_cover_min"]:
        adv.append({
            "kind": "alias-coverage",
            "share": round(1.0 - metrics["alias_coverage"], 4),
            "dominant_category": dominant,
            "message": "%.0f%% of %r's donatable step-shaped argument "
                       "bytes (%s output-matching) are not aliased: "
                       "input AND output state buffers stay live "
                       "across the dispatch.  Pass donate_argnums= on "
                       "the jit -- the static undonated-train-state "
                       "rule (PR 7) names the call sites"
                       % (100 * (1.0 - metrics["alias_coverage"]),
                          label, _fmt_bytes(donatable)),
        })
    adv.sort(key=lambda a: -a["share"])
    return adv


def memory_audit(thresholds=None) -> Dict:
    """Audit every executable the profiling capture surface registered
    for HBM pressure; same walk as ``perf.perf_audit`` (lowering hits
    jax's executable cache).  Returns the ``mxmemory.audit.v1``
    artifact CI diffs against ``ci/memory_baseline.json``.

    Repeated labels (two Dense layers are two ``eager:FullyConnected``
    programs) merge: byte totals sum, ``peak_hbm_bytes`` takes the max
    (peaks of distinct programs do not add -- they are not live
    together by construction of the dispatch order)."""
    import jax
    from ..profiling import store

    th = dict(THRESHOLDS)
    if thresholds:
        th.update(thresholds)
    merged: Dict[str, Dict] = {}
    dominants: Dict[str, Optional[str]] = {}
    for label, fn, args in store.executables():
        try:
            lowered = fn.lower(*args)
            compiled = lowered.compile()
        except Exception:
            continue
        mem = executable_memory(compiled)
        mem["donatable_bytes"] = _donatable_bytes(args, lowered)
        if label in merged:
            agg = merged[label]
            for k, v in mem.items():
                if k == "peak_hbm_bytes":
                    agg[k] = max(agg[k], v)
                else:
                    agg[k] += v
        else:
            merged[label] = mem
            dominants[label] = _dominant_category(compiled)
    execs = {}
    for label, mem in merged.items():
        metrics = _metrics_of(mem)
        execs[label] = {
            "metrics": metrics,
            "advisories": _advisories_for(label, metrics,
                                          dominants.get(label), th),
        }
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "unknown"
    ranked = sorted(
        (dict(a, executable=label)
         for label, e in execs.items() for a in e["advisories"]),
        key=lambda a: -a["share"])
    return {
        "schema": AUDIT_SCHEMA,
        "backend": backend,
        "thresholds": th,
        "executables": execs,
        "advisories": ranked,
    }


def save_audit(path: str, audit=None) -> Dict:
    """Write the current memory audit as JSON (the artifact CI diffs
    against the committed ``ci/memory_baseline.json``)."""
    audit = audit if audit is not None else memory_audit()
    with open(path, "w") as f:
        json.dump(audit, f, indent=1, sort_keys=True)
        f.write("\n")
    return audit


def load_audit(path: str) -> Dict:
    with open(path) as f:
        data = json.load(f)
    if data.get("schema") != AUDIT_SCHEMA:
        raise ValueError("%s is not a %s artifact (schema=%r)"
                         % (path, AUDIT_SCHEMA, data.get("schema")))
    return data


def _audit_tol() -> float:
    try:
        return float(os.environ.get("MXNET_TPU_MEMORY_AUDIT_TOL",
                                    "0.02"))
    except ValueError:
        return 0.02


def diff_audit(baseline: Dict, current: Dict,
               tol: Optional[float] = None) -> List[Diagnostic]:
    """HBM drift of ``current`` vs the blessed ``baseline``:

    - an executable label the baseline never blessed -> error (a new
      program claims HBM nothing gated);
    - an advisory KIND the baseline doesn't carry for that executable
      -> error;
    - ``peak_hbm_bytes`` grown more than ``tol`` (relative; default
      ``MXNET_TPU_MEMORY_AUDIT_TOL`` = 0.02) -> error.

    Shrinkage (smaller peaks, fewer advisories, retired executables)
    passes silently -- re-bless with :func:`save_audit` after an
    intentional change."""
    tol = _audit_tol() if tol is None else tol
    diags: List[Diagnostic] = []
    base_ex = baseline.get("executables", {})
    for label, cur in sorted(current.get("executables", {}).items()):
        base = base_ex.get(label)
        cm = cur.get("metrics", {})
        if base is None:
            diags.append(Diagnostic(
                "memory-drift",
                "unblessed executable %r audits at peak HBM %s; a new "
                "program claims memory nothing gated -- bless via "
                "analysis.memory.save_audit or drop the registration"
                % (label, _fmt_bytes(cm.get("peak_hbm_bytes", 0))),
                node=label))
            continue
        blessed = {a["kind"] for a in base.get("advisories", [])}
        for a in cur.get("advisories", []):
            if a["kind"] not in blessed:
                diags.append(Diagnostic(
                    "memory-drift",
                    "executable %r gained unblessed %r advisory "
                    "(share %.1f%%): %s -- fix the regression or "
                    "re-bless via analysis.memory.save_audit"
                    % (label, a["kind"], 100 * a["share"],
                       a["message"]),
                    node=label))
        b = base.get("metrics", {}).get("peak_hbm_bytes", 0)
        c = cm.get("peak_hbm_bytes", 0)
        if b and c > b * (1.0 + tol):
            diags.append(Diagnostic(
                "memory-drift",
                "executable %r: peak HBM grew %s -> %s (+%.1f%%, "
                "tolerance %.1f%%); the compiled step claims more "
                "memory than the baseline blesses" % (
                    label, _fmt_bytes(b), _fmt_bytes(c),
                    100.0 * (c - b) / b, 100.0 * tol),
                node=label))
    return diags


@rule("memory-drift", "compiled",
      "A registered executable's peak HBM (or its advisory set: "
      "temp-share, alias-coverage) drifted past the committed "
      "ci/memory_baseline.json -- a named, gated memory regression.  "
      "Gate: mxlint --memory-diff.")
def _rule_memory_drift(baseline, current):
    return diff_audit(baseline, current)


# ----------------------------------------------------------------------
# hbm_plan: batch-bucket peak-HBM extrapolation
# ----------------------------------------------------------------------

def _infer_batch_size(leaves) -> Optional[int]:
    """Fallback batch inference: the most frequent leading dimension
    among array leaves.  Correct for servable signatures (one data arg,
    params closed over); pass ``batch_size=`` explicitly for train-step
    signatures where param leading dims compete."""
    counts: Dict[int, int] = {}
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape:
            counts[int(shape[0])] = counts.get(int(shape[0]), 0) + 1
    if not counts:
        return None
    return max(counts, key=lambda k: counts[k])


def _resize_batch(args, batch_size, new_batch):
    import jax

    def _resize(leaf):
        shape = getattr(leaf, "shape", None)
        if shape and int(shape[0]) == batch_size:
            return jax.ShapeDtypeStruct((new_batch,) + tuple(shape[1:]),
                                        leaf.dtype)
        return leaf
    return jax.tree_util.tree_map(_resize, args)


def _peak_at(fn, args) -> int:
    return executable_memory(fn.lower(*args).compile())["peak_hbm_bytes"]


def hbm_plan(label, device_hbm_bytes=None, buckets=None,
             batch_size=None, fn=None, args=None, probe_factor=2) -> Dict:
    """Extrapolate peak HBM across batch buckets for one executable --
    linear in the batch-carried bytes, constant in the params -- and
    answer "what is the largest bucket that fits ``device_hbm_bytes``".

    Two real compiles anchor the line: the registered batch and a probe
    at ``probe_factor`` x (both hit jax's executable cache when already
    dispatched).  ``fn``/``args`` override the ``profiling.store``
    lookup of ``label`` (what the bench sweep and serving validation
    pass directly); ``batch_size`` pins which leading dim is the batch
    (inferred as the most frequent leading dim when omitted).

    Returns ``{"label", "batch_size", "const_bytes",
    "per_item_bytes", "measured", "buckets", "largest_fit_batch",
    "largest_fit_bucket", "device_hbm_bytes"}``; raises ``ValueError``
    when the label is unregistered or no leaf carries the batch dim."""
    import jax
    if fn is None or args is None:
        from ..profiling import store
        for lbl, sfn, sargs in store.executables():
            if lbl == label:
                fn, args = sfn, sargs
                break
        if fn is None or args is None:
            raise ValueError("hbm_plan: no registered executable "
                             "labeled %r (enable MXNET_TPU_PROFILING "
                             "or pass fn=/args=)" % (label,))
    leaves = [x for x in jax.tree_util.tree_leaves(args)
              if hasattr(x, "shape") and hasattr(x, "dtype")]
    if batch_size is None:
        batch_size = _infer_batch_size(leaves)
    if not batch_size or not any(
            getattr(x, "shape", None) and int(x.shape[0]) == batch_size
            for x in leaves):
        raise ValueError("hbm_plan: no argument leaf of %r carries "
                         "batch dim %r" % (label, batch_size))
    b0 = int(batch_size)
    b1 = max(1, b0 * int(probe_factor))
    if b1 == b0:
        b1 = b0 + 1
    peak0 = _peak_at(fn, args)
    peak1 = _peak_at(fn, _resize_batch(args, b0, b1))
    per_item = max(0.0, (peak1 - peak0) / float(b1 - b0))
    const = max(0.0, peak0 - per_item * b0)
    plan = {
        "label": label,
        "batch_size": b0,
        "const_bytes": int(const),
        "per_item_bytes": int(per_item),
        "measured": {str(b0): peak0, str(b1): peak1},
        "device_hbm_bytes": device_hbm_bytes,
        "buckets": [],
        "largest_fit_batch": None,
        "largest_fit_bucket": None,
    }
    if device_hbm_bytes:
        if per_item > 0:
            plan["largest_fit_batch"] = int(
                (device_hbm_bytes - const) // per_item) \
                if device_hbm_bytes > const else 0
        elif peak0 <= device_hbm_bytes:
            plan["largest_fit_batch"] = None    # flat: no batch bound
    for b in sorted(buckets or ()):
        pred = int(const + per_item * int(b))
        fits = (pred <= device_hbm_bytes) if device_hbm_bytes else None
        plan["buckets"].append({"batch": int(b),
                                "predicted_peak_hbm_bytes": pred,
                                "fits": fits})
        if fits:
            plan["largest_fit_bucket"] = int(b)
    return plan


def device_hbm_bytes() -> Optional[int]:
    """Addressable device memory of the first local device (TPU HBM),
    from the runtime's memory_stats; None when the backend does not
    report one (CPU) -- callers skip HBM validation then."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        v = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit")
        return int(v) if v else None
    except Exception:
        return None


# ======================================================================
# Runtime layer: the live-buffer leak sentinel
# ======================================================================

# THE flag the hot paths check: one module-attribute read when off.
_WATCH = os.environ.get("MXNET_TPU_MEMORY_WATCH", "0") != "0"

# sentinel state the /statusz row reads
_STATE = {"censuses": 0, "live_bytes": None, "live_arrays": None,
          "leaks": 0, "last_leak": None}

# the memory.leak chaos action pins arrays here: hidden from the code
# under test, visible to jax.live_arrays() -- the sentinel, not the
# injector, must catch the growth
_PINNED: List[object] = []


def watch_enabled() -> bool:
    """Is the live-buffer watch armed (``MXNET_TPU_MEMORY_WATCH``)?"""
    return _WATCH


def _set_watch(flag):
    """Test/scenario hook: flip the watch without re-importing."""
    global _WATCH
    prev = _WATCH
    _WATCH = bool(flag)
    return prev


def live_census() -> Dict:
    """One census over ``jax.live_arrays()``, bucketed by shape/dtype:
    ``{"bytes_total", "arrays", "buckets": {key: {"count",
    "bytes"}}}``.  Publishes the ``memory.live_bytes`` /
    ``memory.live_arrays`` gauges and the /statusz counters."""
    import jax
    buckets: Dict[str, Dict] = {}
    total = count = 0
    for a in jax.live_arrays():
        try:
            nbytes = int(a.nbytes)
            key = "%s/%s" % (tuple(a.shape), a.dtype)
        except Exception:
            continue
        b = buckets.setdefault(key, {"count": 0, "bytes": 0})
        b["count"] += 1
        b["bytes"] += nbytes
        total += nbytes
        count += 1
    _STATE["censuses"] += 1
    _STATE["live_bytes"] = total
    _STATE["live_arrays"] = count
    from .. import telemetry as _telemetry
    if _telemetry._ENABLED:
        _telemetry.hooks.memory_census(total, count)
    return {"bytes_total": total, "arrays": count, "buckets": buckets}


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class LeakSentinel:
    """Live-bytes leak detection across goodput windows -- the PR-14
    EWMA+MAD machinery pointed at :func:`live_census`.

    ``step()`` once per training step; every ``window_steps`` the
    sentinel censuses live arrays and judges the total against its
    EWMA baseline: a flag needs (a) a warm baseline
    (``min_baseline`` windows), (b) live bytes beyond
    mean + ``mad_k`` deviations, AND (c) a monotonic growth streak of
    at least ``growth_windows`` censuses -- a one-window allocation
    burst never flags, a steady leak always does.  ``note_publish()``
    marks the window publish-guarded: a checkpoint snapshot
    legitimately spikes live bytes, so guarded windows neither judge
    nor teach the baseline (the goodput ledger's checkpoint_stall
    guard, transplanted)."""

    def __init__(self, window_steps=None, mad_k=None, ewma_alpha=0.3,
                 min_baseline=3, growth_windows=2,
                 min_growth_frac=0.02):
        self.window_steps = window_steps if window_steps is not None \
            else _env_int("MXNET_TPU_OBS_GOODPUT_WINDOW", 20)
        self.mad_k = mad_k if mad_k is not None \
            else _env_float("MXNET_TPU_OBS_GOODPUT_MAD_K", 4.0)
        self.ewma_alpha = ewma_alpha
        self.min_baseline = min_baseline
        self.growth_windows = growth_windows
        self.min_growth_frac = min_growth_frac
        self._steps = 0
        self._publishes = 0
        self._index = 0
        self._mean = 0.0
        self._dev = 0.0
        self._n = 0
        self._streak = 0
        self._prev = None          # previous census (bucket growth)
        self._last = None          # last window report (statusz/tests)

    def step(self):
        """One training-step tick; closes a window at the boundary."""
        self._steps += 1
        if self._steps >= self.window_steps:
            self.flush()

    def note_publish(self):
        """Mark this window publish-guarded (a checkpoint snapshot's
        live-bytes spike is expected work, not a leak)."""
        self._publishes += 1

    def flush(self) -> Optional[Dict]:
        """Close the current window now (the trainer's close() tail);
        returns the window report, or None on an empty window."""
        if not self._steps:
            return None
        steps, self._steps = self._steps, 0
        publishes, self._publishes = self._publishes, 0
        index = self._index
        self._index += 1
        census = live_census()
        x = float(census["bytes_total"])
        prev, self._prev = self._prev, census
        report = {"index": index, "steps": steps,
                  "publishes": publishes, "live_bytes": int(x),
                  "live_arrays": census["arrays"], "leak": None}
        if publishes:
            # publish guard: judge nothing, teach nothing -- the spike
            # would poison the baseline exactly like a checkpoint
            # stall poisons the goodput one
            self._last = report
            return report
        grew = prev is not None and x > prev["bytes_total"]
        self._streak = self._streak + 1 if grew else 0
        if self._n >= self.min_baseline:
            thresh = self._mean + self.mad_k * max(
                self._dev, 0.05 * self._mean, 1.0)
            moved = x - self._mean
            if x > thresh and self._streak >= self.growth_windows \
                    and moved >= self.min_growth_frac * max(
                        self._mean, 1.0):
                bucket, growth = self._top_growing(prev, census)
                report["leak"] = {
                    "live_bytes": int(x),
                    "baseline_bytes": int(self._mean),
                    "growth_bytes": int(growth),
                    "bucket": bucket,
                    "streak": self._streak,
                }
                _STATE["leaks"] += 1
                _STATE["last_leak"] = dict(report["leak"],
                                           window=index)
                from .. import telemetry as _telemetry
                if _telemetry._ENABLED:
                    _telemetry.hooks.memory_leak(
                        bucket, int(growth), int(x), index)
        # EWMA update (mean + absolute-deviation MAD analog); flagged
        # windows update too -- a sustained shift becomes the new
        # normal instead of alerting forever (the goodput contract)
        if self._n == 0:
            self._mean, self._dev, self._n = x, 0.0, 1
        else:
            a = self.ewma_alpha
            self._dev = (1 - a) * self._dev + a * abs(x - self._mean)
            self._mean = (1 - a) * self._mean + a * x
            self._n += 1
        self._last = report
        return report

    def _top_growing(self, prev, census):
        """The shape bucket that grew the most vs the previous census
        -- what the leak report NAMES."""
        prev_buckets = (prev or {}).get("buckets", {})
        best, best_growth = None, 0
        for key, b in census["buckets"].items():
            growth = b["bytes"] - prev_buckets.get(
                key, {"bytes": 0})["bytes"]
            if growth > best_growth:
                best, best_growth = key, growth
        return best or "<none>", best_growth

    def last(self) -> Optional[Dict]:
        return self._last

    def baseline(self) -> Dict:
        """EWMA state (tests)."""
        return {"mean": self._mean, "dev": self._dev, "n": self._n}


_SENTINEL: Optional[LeakSentinel] = None


def sentinel(**kwargs) -> LeakSentinel:
    """Get-or-create the process LeakSentinel (what ContinuousTrainer
    ticks when ``MXNET_TPU_MEMORY_WATCH=1``)."""
    global _SENTINEL
    if _SENTINEL is None:
        _SENTINEL = LeakSentinel(**kwargs)
    return _SENTINEL


def reset_watch():
    """Drop the sentinel, pins, and /statusz counters (tests)."""
    global _SENTINEL
    _SENTINEL = None
    _PINNED.clear()
    _STATE.update({"censuses": 0, "live_bytes": None,
                   "live_arrays": None, "leaks": 0, "last_leak": None})


# -- chaos integration -------------------------------------------------

def pin_action(ctx):
    """The ``memory.leak`` chaos action: allocate a device array and
    pin it in a hidden module list, so live bytes grow monotonically
    and the SENTINEL (not the injector) must catch the leak.  Arm
    with::

        chaos.on("memory.leak", memory.pin_action)

    ``ctx`` may carry ``nbytes`` (default 1 MiB per fire)."""
    import jax.numpy as jnp
    nbytes = int(ctx.get("nbytes", 1 << 20))
    _PINNED.append(jnp.zeros((max(1, nbytes // 4),),
                             dtype=jnp.float32))


def pinned_count() -> int:
    return len(_PINNED)


def unpin_all() -> int:
    """Release every chaos-pinned array; returns how many."""
    n = len(_PINNED)
    _PINNED.clear()
    return n


def status_row() -> Dict:
    """The ``/statusz`` memory row: watch arm state, censuses run,
    latest live-buffer totals, leaks flagged, and the last leak's
    attribution."""
    return {"armed": _WATCH, "censuses": _STATE["censuses"],
            "live_bytes": _STATE["live_bytes"],
            "live_arrays": _STATE["live_arrays"],
            "leaks": _STATE["leaks"], "last_leak": _STATE["last_leak"],
            "pinned": len(_PINNED)}
