"""Mixture-of-Experts with expert parallelism (the ``ep`` mesh axis).

TPU-native design: experts are ONE stacked parameter (E, d_in, d_hid)
sharded on its expert axis over ``ep``; routing is a dense one-hot
dispatch einsum, so the token shuffle to expert shards lowers to XLA's
all-to-all over ICI instead of hand-written send/recv.  Capacity is
static (tokens per expert bounded at C), which keeps every shape fixed
for the compiler -- the standard TPU MoE recipe (GShard/Switch), not a
translation of any CPU-style dynamic routing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ..gluon.block import HybridBlock


class MixtureOfExperts(HybridBlock):
    """Top-1 (Switch) MoE feed-forward layer (reference pattern: the
    published Switch-Transformer recipe; the reference framework has no
    MoE -- this is TPU-native net-new surface the ``ep`` axis needs).

    Input (tokens, d_model) -> gate -> dispatch at capacity ->
    per-expert FFN -> combine.  ``shard(mesh)`` places the stacked
    expert weights over the ``ep`` axis.
    """

    def __init__(self, num_experts, d_model, d_hidden, capacity_factor=1.25,
                 mesh=None, axis="ep", **kwargs):
        super().__init__(**kwargs)
        self._E = int(num_experts)
        self._dm = int(d_model)
        self._dh = int(d_hidden)
        self._cf = float(capacity_factor)
        self._mesh = mesh
        self._axis = axis
        from .. import initializer as init_mod
        # per-expert Xavier fan: the generic Xavier rule would read the
        # stacked (E, d_in, d_out) shape as a conv kernel and mis-scale
        bound = float((6.0 / (d_model + d_hidden)) ** 0.5)
        with self.name_scope():
            self.gate = self.params.get(
                "gate", shape=(d_model, num_experts), init="xavier")
            self.w_up = self.params.get(
                "w_up", shape=(num_experts, d_model, d_hidden),
                init=init_mod.Uniform(bound))
            self.w_down = self.params.get(
                "w_down", shape=(num_experts, d_hidden, d_model),
                init=init_mod.Uniform(bound))

    def shard(self, mesh=None):
        from .tensor_parallel import place_param
        mesh = mesh or self._mesh
        if mesh is None:
            raise MXNetError("no mesh to shard over")
        for p, spec in ((self.w_up, P(self._axis, None, None)),
                        (self.w_down, P(self._axis, None, None)),
                        (self.gate, P())):
            place_param(p, mesh, spec)
        return self

    def hybrid_forward(self, F, x, gate=None, w_up=None, w_down=None):
        from ..ndarray import NDArray
        xv = x._data if isinstance(x, NDArray) else x
        gv = gate._data if isinstance(gate, NDArray) else gate
        uv = w_up._data if isinstance(w_up, NDArray) else w_up
        dv = w_down._data if isinstance(w_down, NDArray) else w_down
        out = _moe_forward(xv, gv, uv, dv, self._E, self._cf)
        return NDArray(out) if isinstance(x, NDArray) else out


def _moe_forward(x, gate_w, w_up, w_down, E, capacity_factor):
    """(T, d) tokens -> (T, d); static-capacity top-1 dispatch."""
    T, d = x.shape
    C = max(1, int(capacity_factor * T / E))

    logits = x @ gate_w                               # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)               # (T,)
    gate_val = jnp.max(probs, axis=-1)                # (T,)

    # position of each token within its expert's queue
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)       # (T, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot                 # 1-based
    pos_in_expert = jnp.sum(pos, axis=-1) - 1                 # (T,)
    keep = pos_in_expert < C                                  # overflow drops

    # dispatch tensor (T, E, C): token t -> slot (e, c)
    disp = (onehot.astype(x.dtype)[:, :, None]
            * jax.nn.one_hot(jnp.clip(pos_in_expert, 0, C - 1), C,
                             dtype=x.dtype)[:, None, :]
            * keep[:, None, None].astype(x.dtype))
    # all-to-all: (E, C, d) expert inboxes -- XLA shuffles over `ep`
    inbox = jnp.einsum("tec,td->ecd", disp, x)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", inbox, w_up))
    out_e = jnp.einsum("ech,ehd->ecd", h, w_down)
    # combine back to token order, weighted by the gate
    out = jnp.einsum("tec,ecd->td", disp, out_e)
    return out * gate_val[:, None]


def moe_load_balancing_loss(x, gate_w):
    """Auxiliary load-balance loss (Switch eq. 4): E * sum_e f_e * p_e."""
    T = x.shape[0]
    logits = x @ gate_w
    probs = jax.nn.softmax(logits, axis=-1)
    E = probs.shape[-1]
    expert = jnp.argmax(probs, axis=-1)
    frac = jnp.mean(jax.nn.one_hot(expert, E, dtype=probs.dtype), axis=0)
    prob_mean = jnp.mean(probs, axis=0)
    return E * jnp.sum(frac * prob_mean)
