"""Pipeline parallelism over a ``pp`` mesh axis.

GPipe-style schedule, TPU-native: the S pipeline stages are ONE stacked
parameter tree with a leading stage axis sharded over ``pp`` (each
device holds its stage's weights); microbatches flow stage-to-stage via
``lax.ppermute`` over the ICI ring inside a single ``shard_map`` -- one
compiled program, no host round-trips between stages.  The reference has
no pipeline engine (its model parallelism was per-layer ctx_group
placement with engine-ordered copies); this is the compiler-era
re-design of that row.

Requirements: homogeneous stages (same ``stage_fn``, stacked params) --
the transformer-stack case pipelineing exists for.  Bubble fraction is
(S-1)/(M+S-1) as usual; raise the microbatch count M to amortize.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError


def stack_stage_params(param_trees):
    """Stack S per-stage parameter trees into one tree with a leading
    stage axis (shard it over ``pp``)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *param_trees)


def shard_stacked_params(stacked, mesh, axis="pp"):
    """Place a stacked param tree with its stage axis over ``pp``."""
    def put(leaf):
        spec = P(axis, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, stacked)


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh,
                   axis="pp"):
    """Run ``microbatches`` (M, mb, ...) through S pipelined stages.

    ``stage_fn(stage_params, x) -> x`` applies one stage; stages =
    ``mesh.shape[axis]``; ``stacked_params`` leaves have leading dim S
    (use `stack_stage_params` + `shard_stacked_params`).  Returns the
    (M, mb, ...) outputs.  Differentiable end-to-end (ppermute
    transposes to the reverse rotation).
    """
    from ._shard_map import shard_map as _sm
    shard_map = functools.partial(_sm, check_vma=False)

    S = mesh.shape[axis]
    M = microbatches.shape[0]
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if not leaves:
        raise MXNetError("stacked_params has no array leaves")
    lead = {leaf.shape[0] if leaf.ndim else None for leaf in leaves}
    if lead != {S}:
        raise MXNetError(
            "stacked params have leading stage dim(s) %s but the %r mesh "
            "axis has %d devices; stack exactly one stage per device "
            "(scalar leaves cannot be staged)"
            % (sorted(map(str, lead)), axis, S))
    perm = [(i, (i + 1) % S) for i in range(S)]

    def run(params, xs):
        # params: local (1, ...) slice of the stacked tree; xs: (M, ...)
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        state = jnp.zeros(xs.shape[1:], xs.dtype)
        outputs = jnp.zeros_like(xs)

        def step(t, carry):
            state, outputs = carry
            # stage 0 ingests microbatch t while t < M
            feed_t = jnp.clip(t, 0, M - 1)
            inp = jnp.where(idx == 0,
                            jnp.where(t < M, xs[feed_t],
                                      jnp.zeros_like(state)),
                            state)
            out = stage_fn(local, inp)
            # last stage emits microbatch t-(S-1)
            wt = t - (S - 1)
            wt_c = jnp.clip(wt, 0, M - 1)
            valid = jnp.logical_and(idx == S - 1,
                                    jnp.logical_and(wt >= 0, wt < M))
            outputs = outputs.at[wt_c].set(
                jnp.where(valid, out, outputs[wt_c]))
            state = jax.lax.ppermute(out, axis, perm)
            return state, outputs

        _, outputs = jax.lax.fori_loop(0, M + S - 1, step,
                                       (state, outputs))
        # only the last stage wrote outputs (others hold zeros):
        # psum replicates them everywhere
        return jax.lax.psum(outputs, axis)

    spec_p = jax.tree_util.tree_map(
        lambda leaf: P(axis, *([None] * (leaf.ndim - 1))), stacked_params)
    other_axes = [a for a in mesh.axis_names if a != axis]
    if any(mesh.shape[a] > 1 for a in other_axes):
        raise MXNetError("pipeline_apply uses every device of the mesh "
                         "for stages; pass a 1-D pp mesh")
    fn = shard_map(run, mesh=mesh, in_specs=(spec_p, P()),
                   out_specs=P())
    return fn(stacked_params, microbatches)
