"""Data parallelism over a ``jax.sharding.Mesh``.

TPU-native re-design of the reference's data-parallel stack
(``src/kvstore/comm.h :: CommDevice`` in-process reduce,
``python/mxnet/module/executor_group.py :: DataParallelExecutorGroup``
batch slicing, NCCL allreduce):

- The reference keeps one parameter/gradient copy per GPU and reduces
  between them.  Here there is ONE logical ``jax.Array`` per tensor:
  parameters are *replicated* over the mesh, the batch is *sharded* over
  the ``dp`` axis, and XLA's SPMD partitioner inserts the gradient
  ``psum`` over ICI inside the compiled step -- the comm/compute overlap
  the reference gets from engine-ordered NCCL calls falls out of XLA's
  latency-hiding scheduler.
- ``TrainStep`` compiles forward + loss + backward + optimizer update
  into ONE donated-buffer XLA program: the answer to the reference's
  bulked CachedOp forward/backward plus fused ``multi_sgd_update``
  (``src/operator/optimizer_op.cc``) in a single dispatch.
"""
from __future__ import annotations

import contextlib
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..ndarray import NDArray
from .. import profiling as _profiling
from .. import random as _random_mod
from .mesh import global_mesh, put_replicated, stage_process_local

__all__ = ["replicate_block", "shard_batch", "split_and_load", "TrainStep"]


def _replicated(mesh):
    return NamedSharding(mesh, P())


def _feed_scalar(val, dtype, sharding=None):
    """Per-step host scalar feed (step counter, scheduled lr/wd,
    rescale) as an EXPLICIT device transfer, landed replicated on the
    mesh when one is given.  ``jnp.asarray`` would bind a
    convert_element_type on the Python value -- an IMPLICIT transfer
    that ``transfer_guard("disallow")`` rejects -- and an unplaced feed
    would be resharded device-to-device at dispatch; the guard must
    stay armable over the steady-state step loop so only genuine leaks
    raise (docs/sharding.md).  ``put_replicated`` keeps this valid on a
    multi-host global mesh (the scalar is identical on every rank)."""
    x = np.asarray(val, dtype)
    return put_replicated(x, sharding) if sharding is not None \
        else jax.device_put(x)


def _batch_sharding(mesh, ndim, batch_axis=0, axis_name="dp"):
    spec = [None] * ndim
    spec[batch_axis] = axis_name
    return NamedSharding(mesh, P(*spec))


def replicate_block(block_or_params, mesh):
    """Place every initialized parameter (and its grad buffer) replicated
    over the mesh.  The reference analog is ``ParameterDict.reset_ctx`` to
    a list of contexts; one replicated jax.Array replaces the per-device
    copy list.

    On a multi-host global mesh the value must be IDENTICAL on every
    rank before global placement (each process contributes its
    addressable shards): every not-yet-placed parameter is first synced
    from rank 0 through ONE bucketed host broadcast, then assembled
    into the global replicated array."""
    params = block_or_params
    if hasattr(params, "collect_params"):
        params = params.collect_params()
    sh = _replicated(mesh)
    todo = []
    for p in params.values():
        p._sharding = sh  # consumed by Parameter._finish_init for deferred
        if p._data is None:
            continue
        if not p._data._data.sharding.is_equivalent_to(
                sh, p._data._data.ndim):
            todo.append(p)
    if todo and not getattr(sh, "is_fully_addressable", True):
        from ..distributed import host_broadcast_bucketed
        synced = host_broadcast_bucketed(
            [np.asarray(p._data._data) for p in todo])
        for p, v in zip(todo, synced):
            p._data._data = put_replicated(np.asarray(v), sh)
            if p._data._grad is not None:
                p._data._grad._data = put_replicated(
                    np.asarray(p._data._grad._data), sh)
    else:
        for p in todo:
            p._data._data = jax.device_put(p._data._data, sh)
            if p._data._grad is not None:
                p._data._grad._data = jax.device_put(p._data._grad._data,
                                                     sh)
    return block_or_params


def shard_batch(data, mesh, batch_axis=0, axis_name="dp"):
    """Shard one batch array over the mesh's data-parallel axis.

    Returns an NDArray backed by a single global jax.Array whose shards
    live on the mesh devices (the reference's
    ``DataParallelExecutorGroup`` batch slicing, done by sharding).  On
    a multi-host mesh the input is this process's LOCAL batch and the
    result is the (nproc x local) global batch
    (``mesh.stage_process_local``)."""
    x = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    sh = _batch_sharding(mesh, x.ndim, batch_axis, axis_name)
    if getattr(sh, "is_fully_addressable", True):
        n = mesh.shape[axis_name]
        if x.shape[batch_axis] % n:
            raise MXNetError(
                "batch axis %d (size %d) not divisible by %s=%d"
                % (batch_axis, x.shape[batch_axis], axis_name, n))
    return NDArray(stage_process_local(x, sh))


def split_and_load(data, ctx_list=None, mesh=None, batch_axis=0,
                   even_split=True):
    """Reference: ``gluon.utils.split_and_load`` -- slice a batch across
    devices.  With ``mesh`` given, returns a one-element list holding a
    single mesh-sharded NDArray (the TPU-idiomatic form); with
    ``ctx_list``, returns per-context slices (API compatibility)."""
    from ..ndarray import array as nd_array
    if mesh is not None:
        return [shard_batch(data, mesh, batch_axis)]
    if not ctx_list:
        raise MXNetError("split_and_load needs ctx_list or mesh")
    if isinstance(data, NDArray):
        data = data.asnumpy()
    data = np.asarray(data)
    n = len(ctx_list)
    size = data.shape[batch_axis]
    if even_split and size % n:
        raise MXNetError("batch size %d not divisible by %d contexts"
                         % (size, n))
    step = size // n
    slices = []
    for i, ctx in enumerate(ctx_list):
        lo = i * step
        hi = (i + 1) * step if i < n - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(lo, hi)
        slices.append(nd_array(data[tuple(idx)], ctx=ctx))
    return slices


# ----------------------------------------------------------------------
# Functional optimizer update (traced)
# ----------------------------------------------------------------------

class _TracedCount(dict):
    """Stands in for ``Optimizer._index_update_count`` during tracing so
    the per-step counter ``t`` is a traced input, not a baked constant."""

    def __init__(self, t):
        super().__init__()
        self._t = t

    def __getitem__(self, k):
        return self._t

    def __contains__(self, k):
        return True


@contextlib.contextmanager
def _scalar_feed(opt, t, lr_by_idx, wd_by_idx, rescale):
    """Route every host-side scalar the optimizer reads (step count,
    scheduled lr, wd, rescale_grad) to traced inputs, so one compiled
    step stays valid across steps and lr schedules."""
    orig = (opt._update_count, opt._get_lr, opt._get_wd,
            opt._index_update_count, opt.rescale_grad)
    opt._update_count = lambda index: None
    opt._index_update_count = _TracedCount(t)
    opt._get_lr = lambda index: lr_by_idx[index]
    opt._get_wd = lambda index: wd_by_idx[index]
    opt.rescale_grad = rescale
    try:
        yield
    finally:
        (opt._update_count, opt._get_lr, opt._get_wd,
         opt._index_update_count, opt.rescale_grad) = orig


def _wrap_state(s):
    if s is None:
        return None
    if isinstance(s, (tuple, list)):
        return tuple(_wrap_state(x) for x in s)
    if isinstance(s, NDArray):
        return NDArray(s._data)
    # raw jax array / tracer leaf (inside jit): wrap so the optimizer's
    # NDArray-rebinding update code works unchanged under trace
    return NDArray(s)


def _select_state(pred, new, old):
    """Elementwise select between updated and original optimizer state
    trees (new leaves are NDArray-wrapped, old leaves raw arrays)."""
    if new is None:
        return None
    if isinstance(new, (tuple, list)):
        return tuple(_select_state(pred, n, o) for n, o in zip(new, old))
    nv = new._data if isinstance(new, NDArray) else new
    ov = old._data if isinstance(old, NDArray) else old
    import jax.numpy as _jnp
    return _jnp.where(pred, nv, ov)


def _state_leaves(s):
    if s is None:
        return []
    if isinstance(s, (tuple, list)):
        out = []
        for x in s:
            out.extend(_state_leaves(x))
        return out
    if isinstance(s, NDArray):
        return [s]
    return []


class TrainStep:
    """One fully-compiled SPMD training step.

    ``step = TrainStep(net, loss_fn, trainer, mesh)`` then
    ``loss = step(data, label)``: forward, loss, backward, and the
    optimizer update for every parameter run as a single XLA program with
    parameter/state buffers donated.  With a mesh, the batch is sharded
    over ``dp`` and gradients come out replicated via an XLA-inserted
    ``psum`` over ICI.

    Uses the Trainer's own optimizer and updater state, so
    ``trainer.save_states()`` / lr schedules keep working, and
    interleaves with eager ``trainer.step()`` if needed.
    """

    def __init__(self, block, loss_fn, trainer, mesh=None, batch_axis=0,
                 axis_name="dp", donate=True):
        self._block = block
        self._loss_fn = loss_fn
        self._trainer = trainer
        if mesh is None and jax.process_count() > 1:
            # multi-host world: default to ONE SPMD program over the
            # global mesh -- gradients allreduce in-graph (GSPMD psum),
            # the kvstore is an init-time veneer (docs/distributed.md)
            mesh = global_mesh()
        self._mesh = mesh
        self._batch_axis = batch_axis
        self._axis_name = axis_name
        if donate and mesh is not None and jax.process_count() > 1 \
                and jax.default_backend() == "cpu":
            # jaxlib 0.4.x gloo CPU collectives + donated buffers
            # corrupt the heap after a few dispatches (glibc "corrupted
            # double-linked list" abort, reproduced in-suite); donation
            # is an HBM optimization with no meaning for host memory,
            # so the multi-process CPU/gloo path runs undonated.  TPU
            # pods (ICI collectives) keep donation.
            donate = False
        self._donate = donate
        self._cache = {}
        if mesh is not None:
            replicate_block(block, mesh)

    # -- state plumbing ------------------------------------------------
    def _ensure_states(self):
        tr = self._trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        elif getattr(tr._kvstore, "_is_dist", False):
            # late deferred-init params (materialized by the probe
            # forward) still need the one-time rank-0 sync; bucketed,
            # init-time only -- the step itself moves no host bytes
            tr._sync_initial_params()
        upd = tr._updater
        opt = tr._optimizer
        for i, p in enumerate(tr._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if i not in upd.states:
                upd.states[i] = opt.create_state_multi_precision(i, p.data())
        if self._mesh is not None:
            sh = _replicated(self._mesh)
            for s in upd.states.values():
                for leaf in _state_leaves(s):
                    if not leaf._data.sharding.is_equivalent_to(sh, leaf._data.ndim):
                        leaf._data = put_replicated(leaf._data, sh)

    def _diff_indices(self):
        tr = self._trainer
        return [i for i, p in enumerate(tr._params)
                if p.grad_req != "null" and p._data is not None]

    def _stage_io(self, data, label, shift=0):
        """Stage one (data, label) pair for dispatch.  Host batches land
        through the EXPLICIT staging primitives (guard-clean under
        ``transfer_guard("disallow")``); device arrays reshard only when
        their sharding differs from the target.  With a mesh the batch
        axis shards over ``dp`` -- and on a multi-host global mesh the
        input is this process's LOCAL batch, staged as its slice of the
        global batch (``mesh.stage_process_local``), so the compiled
        step is ONE SPMD program over pre-sharded inputs."""
        if self._mesh is None:
            if not isinstance(data, NDArray):
                data = NDArray(jnp.asarray(data))
            if not isinstance(label, NDArray):
                label = NDArray(jnp.asarray(label))
            return data, label
        dx = data._data if isinstance(data, NDArray) else data
        lx = label._data if isinstance(label, NDArray) else label
        if not isinstance(dx, jax.Array):
            dx = np.asarray(dx)
        if not isinstance(lx, jax.Array):
            lx = np.asarray(lx)
        if getattr(dx, "ndim", 0):
            want = _batch_sharding(self._mesh, dx.ndim,
                                   self._batch_axis + shift,
                                   self._axis_name)
            lsh = _batch_sharding(self._mesh, lx.ndim, shift,
                                  self._axis_name)
            dx = stage_process_local(dx, want)
            lx = stage_process_local(lx, lsh)
        return NDArray(dx), NDArray(lx)

    # -- compilation ---------------------------------------------------
    def _build(self, ivals, training):
        tr = self._trainer
        opt = tr._optimizer
        block = self._block
        loss_fn = self._loss_fn
        idxs = self._diff_indices()
        pure_fn, pnames, pmap = block.functionalize(training=training)
        name_by_idx = {i: tr._params[i].name for i in idxs}
        def step_fn(pvals, svals, data, label, rng, t, lrs, wds, rescale,
                    loss_scale):
            def loss_of(diff_pvals):
                merged = dict(pvals)
                merged.update(diff_pvals)
                outs, aux = pure_fn(merged, [data], rng)
                out_nd = [NDArray(o) for o in outs]
                l = loss_fn(out_nd[0] if len(out_nd) == 1 else out_nd,
                            NDArray(label))
                ldata = l._data if isinstance(l, NDArray) else l
                # Sum (not mean): the reference seeds backward with ones
                # over the batch loss and rescales by 1/batch_size in the
                # optimizer (Trainer.step semantics).  loss_scale is the
                # fp16 AMP scale (1.0 otherwise); rescale folds in its
                # inverse.
                return jnp.sum(ldata) * loss_scale, (jnp.mean(ldata), aux)

            diff_pvals = {name_by_idx[i]: pvals[name_by_idx[i]] for i in idxs}
            grads_and_aux = jax.value_and_grad(loss_of, has_aux=True)(
                diff_pvals)
            (_, (mean_loss, aux)), grads = grads_and_aux

            # Branchless fp16 overflow skip: if any gradient is non-finite
            # the select below keeps the old weights/states (the XLA
            # answer to the reference's skip-update-on-overflow).  ONE
            # fused isfinite-reduction over the dtype-bucketed gradient
            # set (the numerics sentinel's in-graph form) -- one boolean
            # output, no extra host sync on the clean path.
            from ..analysis import numerics as _numerics
            all_finite = _numerics.finite_tree(
                jax.tree_util.tree_leaves(grads))

            lr_map = {i: lrs[k] for k, i in enumerate(idxs)}
            wd_map = {i: wds[k] for k, i in enumerate(idxs)}
            # Start from the full pvals: every parameter buffer is donated,
            # so every one must come back out (unchanged ones alias
            # through), or frozen params would be left deleted.
            new_w = dict(pvals)
            new_s = {}
            from ..kernels import optimizer_update as _kopt
            with _scalar_feed(opt, t, lr_map, wd_map, rescale):
                if _kopt.bucket_active(opt):
                    # kernel tier (MXNET_TPU_KERNELS=1): the LARS/LAMB
                    # update runs over ONE concatenated per-dtype buffer
                    # instead of a per-parameter elementwise-kernel
                    # swarm (docs/kernels.md)
                    upd_w, upd_s = _kopt.bucket_update(
                        opt, [(i, pvals[name_by_idx[i]],
                               grads[name_by_idx[i]], svals.get(i))
                              for i in idxs])
                    for i in idxs:
                        nm = name_by_idx[i]
                        new_w[nm] = jnp.where(all_finite, upd_w[i],
                                              pvals[nm])
                        new_s[i] = _select_state(
                            all_finite, _wrap_state(upd_s[i]),
                            svals.get(i))
                else:
                    for i in idxs:
                        nm = name_by_idx[i]
                        w = NDArray(pvals[nm])
                        g = NDArray(grads[nm])
                        s = _wrap_state(svals.get(i))
                        opt.update_multi_precision(i, w, g, s)
                        new_w[nm] = jnp.where(all_finite, w._data,
                                              pvals[nm])
                        new_s[i] = _select_state(all_finite, s,
                                                 svals.get(i))
            return new_w, new_s, aux, mean_loss, all_finite

        def probe_fn(pvals, data, label, rng, loss_scale):
            # failure-path attribution (numerics sentinel): recompute
            # the gradients from the SAME params/batch/rng -- on a
            # non-finite step the where-select above kept the old
            # weights, so pvals reproduce the faulting step exactly --
            # and hand them back for a host-side per-parameter scan.
            # Never donated, compiled lazily on first non-finite step.
            def loss_of(diff_pvals):
                merged = dict(pvals)
                merged.update(diff_pvals)
                outs, aux = pure_fn(merged, [data], rng)
                out_nd = [NDArray(o) for o in outs]
                l = loss_fn(out_nd[0] if len(out_nd) == 1 else out_nd,
                            NDArray(label))
                ldata = l._data if isinstance(l, NDArray) else l
                return jnp.sum(ldata) * loss_scale, jnp.mean(ldata)

            diff_pvals = {name_by_idx[i]: pvals[name_by_idx[i]]
                          for i in idxs}
            (_, mean_loss), grads = jax.value_and_grad(
                loss_of, has_aux=True)(diff_pvals)
            return grads, mean_loss

        jit_kwargs = {}
        if self._mesh is not None:
            mesh = self._mesh
            rep = _replicated(mesh)

            def rep_tree(tree):
                return jax.tree_util.tree_map(lambda _: rep, tree)

            data_sh = _batch_sharding(mesh, len(ivals[0].shape),
                                      self._batch_axis, self._axis_name)
            label_sh = _batch_sharding(mesh, len(ivals[1].shape),
                                       0, self._axis_name)
            jit_kwargs["in_shardings"] = (
                None, None, data_sh, label_sh, rep, rep, rep, rep, rep, rep)
        if self._donate:
            jit_kwargs["donate_argnums"] = (0, 1)
        # the attribution probe must NOT donate: it re-reads the live
        # param buffers after a failed step
        return (jax.jit(step_fn, **jit_kwargs),
                jax.jit(probe_fn),  # mxlint: disable=undonated-train-state
                idxs, pnames, pmap)

    # -- multi-step scan ----------------------------------------------
    def _build_scan(self, ivals, training):
        """Compile a ``lax.scan`` over K training steps: one dispatch runs
        K full fwd+bwd+update iterations on stacked batches (K, B, ...).

        TPU-idiomatic epoch inner loop: removes per-step host dispatch
        entirely (the reference's analog is engine-queued bulk execution;
        here the loop itself is on device).
        """
        fn_single, _probe, idxs, pnames, pmap = self._build(
            [NDArray(ivals[0]._data[0]), NDArray(ivals[1]._data[0])],
            training)
        aux_names = None

        def scan_fn(pvals, svals, datas, labels, rng, t0, lrs, wds,
                    rescale, loss_scale):
            k = datas.shape[0]

            def body(carry, xs):
                pv, sv, t = carry
                data, label, key = xs
                new_w, new_s, aux, mean_loss, _fin = fn_single(
                    pv, sv, data, label, key, t, lrs, wds, rescale,
                    loss_scale)
                # thread updated BN running stats back in for the next step
                new_w = dict(new_w)
                for n, v in aux.items():
                    new_w[n] = v
                return (new_w, new_s, t + 1), mean_loss

            keys = jax.random.split(rng, k)
            (pv, sv, t), losses = jax.lax.scan(
                body, (pvals, svals, t0), (datas, labels, keys))
            return pv, sv, t, losses

        jit_kwargs = {}
        if self._mesh is not None:
            mesh = self._mesh
            rep = _replicated(mesh)
            data_sh = _batch_sharding(mesh, ivals[0]._data.ndim,
                                      self._batch_axis + 1, self._axis_name)
            label_sh = _batch_sharding(mesh, ivals[1]._data.ndim, 1,
                                       self._axis_name)
            jit_kwargs["in_shardings"] = (
                None, None, data_sh, label_sh, rep, rep, rep, rep, rep, rep)
        if self._donate:
            jit_kwargs["donate_argnums"] = (0, 1)
        return jax.jit(scan_fn, **jit_kwargs), idxs, pnames, pmap

    def run_steps(self, data, label, batch_size=None):
        """Run K training steps in ONE compiled dispatch.

        ``data``/``label`` carry a leading steps axis: (K, B, ...).
        Returns the per-step mean losses as an NDArray of shape (K,).
        BatchNorm running stats, optimizer state, and the step counter all
        thread through the on-device loop.
        """
        from .. import amp as _amp
        from ..ndarray import bulk as _bulk
        tr = self._trainer
        opt = tr._optimizer
        if getattr(tr, "_amp_loss_scaler", None) is not None:
            raise MXNetError(
                "run_steps does not support fp16 dynamic loss scaling "
                "(the scaler's growth/backoff counters live on the host); "
                "use bf16 AMP or per-step __call__ for fp16")
        for p in tr._params:
            if p._data is not None and p.dtype is not None \
                    and p._data._data.dtype != p.dtype:
                p.cast(p.dtype)
        self._ensure_states()
        # leading axis is the step index; batch axis shifts right by 1
        data, label = self._stage_io(data, label, shift=1)
        if any(p._deferred_init is not None
               for p in self._block._all_params()):
            from .. import autograd as _ag
            with _ag.pause():
                self._block(NDArray(data._data[0]))
            self._ensure_states()
        k = data.shape[0]
        key = ("scan", tuple(data.shape), str(data.dtype),
               tuple(label.shape), str(label.dtype), _amp.policy_token())
        entry = self._cache.get(key)
        if entry is None:
            entry = self._build_scan([data, label], True)
            self._cache[key] = entry
        fn, idxs, pnames, pmap = entry

        t_start = opt._index_update_count.get(
            idxs[0], opt.begin_num_update) + 1 if idxs else opt.num_update
        # lr/wd are read from the schedule at the BLOCK START and held for
        # the K in-scan steps (the schedule is host-side Python, so it
        # cannot be traced per step); callers with fast-moving schedules
        # should pick K accordingly
        num_update_at_start = max(opt.num_update, t_start)
        saved_num_update = opt.num_update
        opt.num_update = num_update_at_start
        rep = _replicated(self._mesh) if self._mesh is not None else None
        lrs = _feed_scalar([opt._get_lr(i) for i in idxs], np.float32, rep)
        wds = _feed_scalar([opt._get_wd(i) for i in idxs], np.float32, rep)
        opt.num_update = saved_num_update
        for i in idxs:
            opt._index_update_count[i] = \
                opt._index_update_count.get(i, opt.begin_num_update) + k
            opt.num_update = max(opt._index_update_count[i], opt.num_update)
        t = _feed_scalar(t_start, np.int32, rep)
        bs = batch_size if batch_size is not None \
            else data.shape[self._batch_axis + 1]
        rescale = _feed_scalar(tr._scale / bs, np.float32, rep)
        loss_scale = _feed_scalar(1.0, np.float32, rep)
        upd = tr._updater
        pvals = {n: pmap[n]._data._data for n in pnames}
        svals = {i: jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, NDArray) else x,
            upd.states.get(i),
            is_leaf=lambda x: isinstance(x, NDArray) or x is None)
            for i in idxs}
        rng = _random_mod.next_key()
        args = (pvals, svals, data._data, label._data, rng, t, lrs, wds,
                rescale, loss_scale)
        self._last_call = (fn, jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args))
        # the jit donates the param/state buffers; any still-pending
        # bulked-eager region referencing them must execute first
        _bulk.flush()
        t0p = time.perf_counter() if _profiling._ENABLED else None
        new_w, new_s, _t, losses = fn(*args)
        if t0p is not None:
            label = "train_scan:%s" % type(self._block).__name__
            self._profiling_hook(label, fn, t0p,
                                 time.perf_counter() - t0p, k * bs)
        for n in pnames:
            pmap[n]._data._data = new_w[n]
        for i in idxs:
            s = upd.states.get(i)
            flat_new = jax.tree_util.tree_leaves(new_s[i])
            for leaf, nv in zip(_state_leaves(s), flat_new):
                leaf._data = nv
        # aux (running stats) were threaded inside new_w; rebind Parameters
        for p in self._block._all_params():
            if p.name in pnames and p.grad_req == "null" \
                    and p._data is not None:
                grad = p._data._grad
                p._data = NDArray(new_w[p.name])
                p._data._grad = grad
        return NDArray(losses)

    def _profiling_hook(self, label, fn, t0, dispatch_s, items):
        """mx.profiling capture for one dispatched step: register the
        compiled program for lazy cost analysis, feed the roofline's
        step clock, and drop a timeline span.  On a synchronous backend
        (CPU CI) the dispatch wall IS the step time; on async TPU
        dispatch the steady-state loop is back-pressured by buffer
        donation, so per-call wall converges to step time -- callers
        with externally synced windows can refine via
        ``profiling.record_step``."""
        from ..profiling import timeline
        _profiling.capture_jit(label, fn, self._last_call[1],
                               key=("train_step", id(fn)),
                               kind="train_step")
        _profiling.record_step(label, dispatch_s, items=items)
        timeline.record(label, t0, dispatch_s,
                        {"items": items, "donated": self._donate})
        if self._donate:
            timeline.instant(label + ".donate",
                             {"buffers": "params+opt_state"})

    def cost_analysis(self):
        """XLA's cost analysis of the most recently dispatched compiled
        program -- ``{"flops": ..., "bytes accessed": ..., ...}`` or None.
        Powers the bench's MFU report.  Cheap after the first call: the
        lowering hits the jit compile cache."""
        if getattr(self, "_last_call", None) is None:
            return None
        fn, arg_shapes = self._last_call
        try:
            ca = fn.lower(*arg_shapes).compile().cost_analysis()
            if isinstance(ca, list):
                ca = ca[0]
            return dict(ca)
        except Exception:
            return None

    # -- call ----------------------------------------------------------
    def __call__(self, data, label=None, batch_size=None):
        from .. import autograd as _ag
        from ..ndarray import bulk as _bulk
        if label is None:
            # a fed batch (dataio.DeviceFeed) carries device-resident
            # data+label; unpack without any re-transfer
            from ..dataio import DeviceBatch
            if isinstance(data, DeviceBatch):
                data, label = data.data, data.label
            if label is None:
                raise MXNetError(
                    "TrainStep needs (data, label) or a DeviceBatch "
                    "with a label component")
        tr = self._trainer
        opt = tr._optimizer
        # value dtype must match the declared Parameter dtype BEFORE
        # optimizer states are created from it (a drifted value would
        # bake mismatched state dtypes in for the whole run);
        # Parameter.cast also reallocates the grad buffer
        for p in tr._params:
            if p._data is not None and p.dtype is not None \
                    and p._data._data.dtype != p.dtype:
                p.cast(p.dtype)
        self._ensure_states()
        data, label = self._stage_io(data, label)
        if any(p._deferred_init is not None
               for p in self._block._all_params()):
            # materialize deferred shapes with one eager forward;
            # Parameter._sharding (set by replicate_block) places them
            # replicated on the mesh
            with _ag.pause():
                self._block(data)
            self._ensure_states()

        from ..analysis import numerics as _numerics
        from .. import chaos as _chaos
        # numerics.nonfinite chaos point: poison_action marks the box
        # and THIS step injects the NaN into its own batch, so the
        # fault flows through forward/backward and must be caught by
        # the sentinel, not the injector (docs/numerics.md)
        _box = {}
        _chaos.fail_point("numerics.nonfinite", box=_box,
                          step=opt.num_update + 1)
        if _box.get("poison"):
            data = _numerics.poison_nd(data)

        training = True
        from .. import amp as _amp
        key = (tuple(data.shape), str(data.dtype), tuple(label.shape),
               str(label.dtype), training, _amp.policy_token())
        entry = self._cache.get(key)
        if entry is None:
            entry = self._build([data, label], training)
            self._cache[key] = entry
        fn, probe, idxs, pnames, pmap = entry

        # host-side per-step bookkeeping (matches Optimizer._update_count)
        for i in idxs:
            opt._index_update_count[i] = \
                opt._index_update_count.get(i, opt.begin_num_update) + 1
            opt.num_update = max(opt._index_update_count[i], opt.num_update)
        rep = _replicated(self._mesh) if self._mesh is not None else None
        t = _feed_scalar(opt._index_update_count[idxs[0]] if idxs else
                         opt.num_update, np.int32, rep)
        lrs = _feed_scalar([opt._get_lr(i) for i in idxs], np.float32, rep)
        wds = _feed_scalar([opt._get_wd(i) for i in idxs], np.float32, rep)
        bs = batch_size if batch_size is not None \
            else data.shape[self._batch_axis]
        scaler = getattr(tr, "_amp_loss_scaler", None)
        ls = scaler.loss_scale if scaler is not None else 1.0
        rescale = _feed_scalar(tr._scale / bs / ls, np.float32, rep)
        loss_scale = _feed_scalar(ls, np.float32, rep)

        upd = tr._updater
        pvals = {n: pmap[n]._data._data for n in pnames}
        svals = {i: jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, NDArray) else x,
            upd.states.get(i),
            is_leaf=lambda x: isinstance(x, NDArray) or x is None)
            for i in idxs}
        rng = _random_mod.next_key()

        args = (pvals, svals, data._data, label._data, rng, t, lrs, wds,
                rescale, loss_scale)
        self._last_call = (fn, jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args))
        # the jit donates the param/state buffers; any still-pending
        # bulked-eager region referencing them must execute first
        _bulk.flush()
        t0p = time.perf_counter() if _profiling._ENABLED else None
        new_w, new_s, aux, mean_loss, all_finite = fn(*args)
        if t0p is not None:
            label = "train_step:%s" % type(self._block).__name__
            self._profiling_hook(label, fn, t0p,
                                 time.perf_counter() - t0p, bs)
        finite_host = None
        if scaler is not None:
            # host sync only in fp16 mode: the scaler's growth/backoff
            # counters live on the host (reference LossScaler semantics)
            finite_host = bool(np.asarray(all_finite))
            scaler.update_scale(not finite_host)

        # rebind updated weights/states/aux into the framework objects
        # (ALL params: buffers were donated, unchanged ones aliased through)
        for n in pnames:
            pmap[n]._data._data = new_w[n]
        for i in idxs:
            s = upd.states.get(i)
            flat_new = jax.tree_util.tree_leaves(new_s[i])
            for leaf, nv in zip(_state_leaves(s), flat_new):
                leaf._data = nv
        for p in self._block._all_params():
            if p.name in aux:
                grad = p._data._grad if p._data is not None else None
                p._data = NDArray(aux[p.name])
                p._data._grad = grad

        if _numerics.check_enabled():
            # the sentinel reads the ONE boolean the compiled step
            # already produced (shared with the fp16 scaler's fetch);
            # framework state was rebound above -- on a non-finite step
            # the where-select kept the pre-step weights, so raising
            # here leaves the model consistent and restartable
            t0s = time.perf_counter()
            if finite_host is None:
                finite_host = bool(np.asarray(all_finite))
            _numerics.note_check(time.perf_counter() - t0s)
            if not finite_host:
                step_no = opt.num_update
                # attribution pass: recompute this step's gradients
                # from the restored params + the same batch/rng, then
                # scan per-parameter host-side (failure path only)
                grads, probe_loss = probe(new_w, args[2], args[3],
                                          args[4], args[9])
                names = [tr._params[i].name for i in idxs]
                named = [(nm, grads[nm]) for nm in names if nm in grads]
                hit = _numerics.attribute_nonfinite(
                    named + [("loss", probe_loss)])
                param, kind = hit if hit is not None else (
                    "<unattributed>", "nonfinite")
                _numerics.record_nonfinite(param, step_no, kind)
                raise _numerics.NonFiniteError(param, step_no, kind)
        return NDArray(mean_loss)
