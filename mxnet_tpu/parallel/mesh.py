"""Device mesh utilities.

TPU-native replacement for the reference's device topology layer
(``src/kvstore/gpu_topology.h`` tree schedules, NCCL communicators):
on TPU the ICI torus is addressed through a ``jax.sharding.Mesh`` and
XLA emits the collectives, so "topology-aware scheduling" reduces to
picking mesh axes (SURVEY.md §2.4).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError

__all__ = ["make_mesh", "Mesh", "NamedSharding", "PartitionSpec",
           "local_devices", "default_mesh", "global_mesh", "AXIS_ROLES",
           "put_replicated", "stage_process_local"]

# Canonical mesh-axis vocabulary.  Axis names are arbitrary strings to
# XLA, but the parallel layers, the docs, and the sharding sanitizer
# (mxnet_tpu.analysis.sharding, rule ``mesh-axis-unknown``) all speak
# these five roles; a PartitionSpec naming an axis outside this table
# AND outside every Mesh/make_mesh construction in the linted tree is
# flagged, because XLA silently replicates over unknown axes instead of
# sharding.  Project-specific axes are declared simply by building a
# mesh with them.
AXIS_ROLES = OrderedDict([
    ("dp", "data parallel: batch dim sharded, gradients psum over ICI"),
    ("tp", "tensor (model) parallel: Megatron column/row weight splits"),
    ("pp", "pipeline parallel: stacked stage params, ppermute ring"),
    ("sp", "sequence/context parallel: ring-attention KV rotation"),
    ("ep", "expert parallel: stacked MoE experts, all-to-all dispatch"),
])


def local_devices(platform=None):
    if platform:
        try:
            return [d for d in jax.devices() if d.platform == platform] or \
                jax.devices(platform)
        except RuntimeError:
            return []
    return jax.devices()


def make_mesh(axes, devices=None):
    """Build a Mesh from ``{'dp': 4, 'tp': 2}``-style axis sizes.

    ``-1`` for one axis means "all remaining devices".  Axis order follows
    insertion order; put the fastest-varying (innermost, highest-bandwidth)
    axis last, as the scaling-book recipe recommends for ICI.
    """
    axes = OrderedDict(axes)
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise MXNetError("only one mesh axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if -1 in sizes:
        if n % known:
            raise MXNetError("cannot infer -1 axis: %d devices not divisible "
                             "by %d" % (n, known))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise MXNetError("mesh wants %d devices, only %d available"
                         % (total, n))
    mesh_devices = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(mesh_devices, tuple(axes.keys()))


_default_mesh = None


def default_mesh():
    """A 1-D data-parallel mesh over all devices (cached)."""
    global _default_mesh
    if _default_mesh is None or \
            _default_mesh.devices.size != len(jax.devices()):
        _default_mesh = make_mesh({"dp": -1})
    return _default_mesh


_global_meshes = {}


def global_mesh(axes=None):
    """The ONE mesh a multi-host SPMD program runs over: every device
    of every process in the ``jax.distributed`` world (``jax.devices()``
    spans hosts once ``distributed_init`` ran).  Default axes:
    ``{"dp": -1}`` -- pure data parallel; pass e.g.
    ``{"dp": -1, "tp": 2}`` for a 2-D data x model mesh.  Cached per
    (axes, world size), so every caller -- ``TrainStep``, ``DeviceFeed``,
    checkpoint resharding -- agrees on one device order
    (docs/distributed.md)."""
    axes = OrderedDict(axes if axes is not None else {"dp": -1})
    if "dp" not in axes:
        raise MXNetError("global_mesh needs a 'dp' axis (got %r)"
                         % list(axes))
    key = (tuple(axes.items()), len(jax.devices()))
    mesh = _global_meshes.get(key)
    if mesh is None:
        mesh = _global_meshes[key] = make_mesh(axes)
    return mesh


def put_replicated(x, sharding):
    """Place one host/device value replicated onto a (possibly
    multi-host) sharding.  Single-process this is ``jax.device_put``;
    in a multi-process world a host value cannot be device_put onto
    non-addressable devices, so the global array is assembled from this
    process's addressable shards -- callers must have synchronized the
    value across ranks first (``distributed.host_broadcast_bucketed``),
    or ranks silently diverge."""
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    x = np.asarray(x)
    # make_array_from_callback's internal batched_device_put counts as
    # an IMPLICIT transfer under jax.transfer_guard("disallow"), but
    # this call IS the library's explicit placement primitive (morally
    # jax.device_put, which the guard exempts) -- allow it locally so
    # the guard stays armable over the steady-state step loop
    with jax.transfer_guard("allow"):
        return jax.make_array_from_callback(x.shape, sharding,
                                            lambda idx: x[idx])


def stage_process_local(x, sharding):
    """Land one PROCESS-LOCAL batch shard as its slice of the global
    array (``jax.make_array_from_process_local_data``): every process
    contributes its local batch and the result is the (nproc x local)
    global batch sharded per ``sharding``.  Single-process (or already
    correctly sharded) inputs take the plain ``device_put`` path.  The
    staging half of the one-program SPMD contract -- batches arrive
    pre-sharded, the compiled step never re-transfers."""
    if isinstance(x, jax.Array) and \
            x.sharding.is_equivalent_to(sharding, x.ndim):
        return x
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(
            x if isinstance(x, jax.Array) else np.asarray(x), sharding)
    x = np.asarray(x)
    # explicit staging primitive: see put_replicated's guard note
    with jax.transfer_guard("allow"):
        return jax.make_array_from_process_local_data(sharding, x)
