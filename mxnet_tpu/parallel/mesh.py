"""Device mesh utilities.

TPU-native replacement for the reference's device topology layer
(``src/kvstore/gpu_topology.h`` tree schedules, NCCL communicators):
on TPU the ICI torus is addressed through a ``jax.sharding.Mesh`` and
XLA emits the collectives, so "topology-aware scheduling" reduces to
picking mesh axes (SURVEY.md §2.4).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..base import MXNetError

__all__ = ["make_mesh", "Mesh", "NamedSharding", "PartitionSpec",
           "local_devices", "default_mesh", "AXIS_ROLES"]

# Canonical mesh-axis vocabulary.  Axis names are arbitrary strings to
# XLA, but the parallel layers, the docs, and the sharding sanitizer
# (mxnet_tpu.analysis.sharding, rule ``mesh-axis-unknown``) all speak
# these five roles; a PartitionSpec naming an axis outside this table
# AND outside every Mesh/make_mesh construction in the linted tree is
# flagged, because XLA silently replicates over unknown axes instead of
# sharding.  Project-specific axes are declared simply by building a
# mesh with them.
AXIS_ROLES = OrderedDict([
    ("dp", "data parallel: batch dim sharded, gradients psum over ICI"),
    ("tp", "tensor (model) parallel: Megatron column/row weight splits"),
    ("pp", "pipeline parallel: stacked stage params, ppermute ring"),
    ("sp", "sequence/context parallel: ring-attention KV rotation"),
    ("ep", "expert parallel: stacked MoE experts, all-to-all dispatch"),
])


def local_devices(platform=None):
    if platform:
        try:
            return [d for d in jax.devices() if d.platform == platform] or \
                jax.devices(platform)
        except RuntimeError:
            return []
    return jax.devices()


def make_mesh(axes, devices=None):
    """Build a Mesh from ``{'dp': 4, 'tp': 2}``-style axis sizes.

    ``-1`` for one axis means "all remaining devices".  Axis order follows
    insertion order; put the fastest-varying (innermost, highest-bandwidth)
    axis last, as the scaling-book recipe recommends for ICI.
    """
    axes = OrderedDict(axes)
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise MXNetError("only one mesh axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if -1 in sizes:
        if n % known:
            raise MXNetError("cannot infer -1 axis: %d devices not divisible "
                             "by %d" % (n, known))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise MXNetError("mesh wants %d devices, only %d available"
                         % (total, n))
    mesh_devices = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(mesh_devices, tuple(axes.keys()))


_default_mesh = None


def default_mesh():
    """A 1-D data-parallel mesh over all devices (cached)."""
    global _default_mesh
    if _default_mesh is None or \
            _default_mesh.devices.size != len(jax.devices()):
        _default_mesh = make_mesh({"dp": -1})
    return _default_mesh
