"""Version-compat import of ``shard_map``.

jax moved ``shard_map`` from ``jax.experimental.shard_map`` to the
top-level namespace and renamed its replication-check kwarg
(``check_rep`` -> ``check_vma``) across releases; the parallel modules
import from here so they run on either side of the move.
"""
from __future__ import annotations

import inspect

try:                               # jax >= 0.5: top-level
    from jax import shard_map as _shard_map
except ImportError:                # jax 0.4.x: experimental
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _params = inspect.signature(_shard_map).parameters
    _CHECK_KW = "check_vma" if "check_vma" in _params else "check_rep"
except (TypeError, ValueError):
    _CHECK_KW = "check_vma"

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})
