"""Sequence/context parallelism: ring attention over a mesh axis.

The reference scales sequence length only by padding/bucketing within one
device's memory (SURVEY.md §5 "long-context": BucketingModule + fused RNN
kernels).  A TPU-native framework owes more: ring attention shards the
SEQUENCE over a mesh axis, each device holding seq/n of Q/K/V.  KV blocks
rotate around the ring via ``lax.ppermute`` (neighbor hops on the ICI
torus) while each device folds every block into a running online-softmax
(max, sum, acc) carry -- attention memory stays O(seq/n * d) per device
and comm overlaps compute block-by-block.

Composes with data parallelism: mesh {'dp': a, 'sp': b}, batch sharded on
``dp``, sequence on ``sp``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ._shard_map import shard_map

from ..base import MXNetError

__all__ = ["ring_attention", "ring_attention_sharded"]

_NEG_INF = -1e30


def _ring_attention_local(q, k, v, *, axis_name, causal, scale, seq_per):
    """Per-device body (inside shard_map): q/k/v are (bh, seq_local, d)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    bh, sl, d = q.shape
    qf = q.astype(jnp.float32)

    rows_local = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 0)
    cols_local = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 1)
    my_row0 = idx * seq_per

    def block(carry, _):
        m, l, acc, kb, vb, src = carry
        s = jax.lax.dot_general(
            qf, kb.astype(jnp.float32), (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows_g = my_row0 + rows_local
            cols_g = src * seq_per + cols_local
            s = jnp.where(rows_g[None] >= cols_g[None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vb.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        # rotate KV one hop around the ring (ICI neighbor transfer)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        src = (src - 1) % n
        return (m_new, l_new, acc_new, kb, vb, src), None

    m0 = jnp.full((bh, sl, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, sl, 1), jnp.float32)
    acc0 = jnp.zeros((bh, sl, d), jnp.float32)
    (m, l, acc, _, _, _), _ = jax.lax.scan(
        block, (m0, l0, acc0, k, v, idx), None, length=n)
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False, scale=None):
    """Sequence-parallel attention: q/k/v (bh, seq, d) with ``seq`` sharded
    over ``mesh[axis_name]``; returns same-sharded output."""
    if axis_name not in mesh.shape:
        raise MXNetError("mesh has no axis %r" % axis_name)
    n = mesh.shape[axis_name]
    bh, seq, d = q.shape
    if seq % n:
        raise MXNetError("seq %d not divisible by %s=%d" % (seq, axis_name, n))
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    seq_per = seq // n
    body = functools.partial(_ring_attention_local, axis_name=axis_name,
                             causal=causal, scale=scale, seq_per=seq_per)
    spec = P(None, axis_name, None)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False,
                           scale=None):
    """Convenience wrapper taking/returning framework NDArrays, placing
    inputs seq-sharded on the mesh first."""
    from ..ndarray import NDArray
    sh = NamedSharding(mesh, P(None, axis_name, None))
    qd = jax.device_put(q._data if isinstance(q, NDArray) else q, sh)
    kd = jax.device_put(k._data if isinstance(k, NDArray) else k, sh)
    vd = jax.device_put(v._data if isinstance(v, NDArray) else v, sh)
    out = jax.jit(functools.partial(ring_attention, mesh=mesh,
                                    axis_name=axis_name, causal=causal,
                                    scale=scale))(qd, kd, vd)
    return NDArray(out)
