"""Tensor (model) parallelism over mesh axes (reference: the ctx_group
model-parallel examples, e.g. ``example/model-parallel-lstm`` -- re-done
the SPMD way).

Megatron-style sharding expressed as **sharding annotations, not
collectives**: a column-parallel Dense splits its weight's output dim
over the ``tp`` axis, the paired row-parallel Dense splits its input
dim, and XLA's SPMD partitioner inserts the single all-reduce at the
row layer's output.  No NCCL groups, no manual partial sums -- pick a
mesh, annotate, jit (the scaling-book recipe).

Use ``shard_block_tp`` to annotate an existing block's parameters by
rule, or the ``ColumnParallelDense`` / ``RowParallelDense`` layers to
build tp-native models; both make every param carry a NamedSharding
that ``jit`` propagates.
"""
from __future__ import annotations

import re

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock


def place_param(param, mesh, spec):
    """Record + apply a NamedSharding on a Parameter (deferred params
    get it at materialization via Parameter._sharding)."""
    sh = NamedSharding(mesh, spec)
    param._sharding = sh
    if param._data is not None:
        param._data._data = jax.device_put(param._data._data, sh)


_put = place_param  # internal alias used by the layer classes below


class ColumnParallelDense(nn.Dense):
    """Dense with the weight split on the OUTPUT dim over ``tp``
    (reference pattern: Megatron column-parallel linear).  Output stays
    tp-sharded; follow with a RowParallelDense to come back together."""

    def __init__(self, units, mesh=None, axis="tp", **kwargs):
        super().__init__(units, **kwargs)
        self._tp_mesh = mesh
        self._tp_axis = axis

    def shard(self, mesh=None):
        mesh = mesh or self._tp_mesh
        if mesh is None:
            raise MXNetError("no mesh to shard over")
        # weight (units, in): split rows (outputs); bias follows
        _put(self.weight, mesh, P(self._tp_axis, None))
        if getattr(self, "bias", None) is not None:
            _put(self.bias, mesh, P(self._tp_axis))
        return self


class RowParallelDense(nn.Dense):
    """Dense with the weight split on the INPUT dim over ``tp``: the
    partial products all-reduce at the output (XLA inserts the psum)."""

    def __init__(self, units, mesh=None, axis="tp", **kwargs):
        super().__init__(units, **kwargs)
        self._tp_mesh = mesh
        self._tp_axis = axis

    def shard(self, mesh=None):
        mesh = mesh or self._tp_mesh
        if mesh is None:
            raise MXNetError("no mesh to shard over")
        # weight (units, in): split columns (inputs); bias replicated
        _put(self.weight, mesh, P(None, self._tp_axis))
        if getattr(self, "bias", None) is not None:
            _put(self.bias, mesh, P())
        return self


class TensorParallelMLP(HybridBlock):
    """The canonical tp block: column-parallel up-projection, gelu,
    row-parallel down-projection -- ONE all-reduce per MLP, the
    transformer FFN recipe."""

    def __init__(self, hidden, units, mesh=None, axis="tp",
                 activation="gelu", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.up = ColumnParallelDense(hidden, mesh=mesh, axis=axis,
                                          flatten=False)
            self.act = nn.Activation(activation)
            self.down = RowParallelDense(units, mesh=mesh, axis=axis,
                                         flatten=False)

    def shard(self, mesh=None):
        self.up.shard(mesh)
        self.down.shard(mesh)
        return self

    def hybrid_forward(self, F, x):
        return self.down(self.act(self.up(x)))


# default Megatron-ish rules for annotating an existing model:
# (regex on param name) -> PartitionSpec builder given the tp axis name
_DEFAULT_RULES = [
    (r".*(qkv|query|key|value|up|fc1|ffn_1|intermediate).*weight",
     lambda ax: P(ax, None)),
    (r".*(qkv|query|key|value|up|fc1|ffn_1|intermediate).*bias",
     lambda ax: P(ax)),
    (r".*(proj|out|down|fc2|ffn_2|output).*weight",
     lambda ax: P(None, ax)),
    (r".*embed.*weight", lambda ax: P(None, ax)),
]


def shard_block_tp(block, mesh, axis="tp", rules=None):
    """Annotate an existing block's parameters with tp shardings by
    name rule; unmatched params are replicated.  Returns the names that
    were tp-sharded (for asserting coverage in tests)."""
    rules = [(re.compile(pat), fn) for pat, fn in
             (rules or _DEFAULT_RULES)]
    sharded = []
    for p in block.collect_params().values():
        spec = None
        for pat, fn in rules:
            if pat.match(p.name):
                spec = fn(axis)
                break
        if spec is None:
            spec = P()
        else:
            sharded.append(p.name)
        _put(p, mesh, spec)
    return sharded
