"""Parallelism: device meshes, data-parallel sharding, compiled train steps.

TPU-native replacement for the reference's multi-device stack (SURVEY.md
§2.4): ``jax.sharding.Mesh`` + SPMD partitioning replace per-device
parameter copies, CommDevice reduction, and NCCL.
"""
from .mesh import (Mesh, NamedSharding, PartitionSpec, default_mesh,
                   global_mesh, local_devices, make_mesh, put_replicated,
                   stage_process_local)
from .data_parallel import (TrainStep, replicate_block, shard_batch,
                            split_and_load)
from .sequence import ring_attention, ring_attention_sharded
from .tensor_parallel import (ColumnParallelDense, RowParallelDense,
                              TensorParallelMLP, shard_block_tp)
from .pipeline import (pipeline_apply, shard_stacked_params,
                       stack_stage_params)
from .moe import MixtureOfExperts, moe_load_balancing_loss

__all__ = ["Mesh", "NamedSharding", "PartitionSpec", "default_mesh",
           "global_mesh", "local_devices", "make_mesh", "put_replicated",
           "stage_process_local", "TrainStep", "replicate_block",
           "shard_batch", "split_and_load", "ring_attention",
           "ring_attention_sharded", "ColumnParallelDense",
           "RowParallelDense", "TensorParallelMLP", "shard_block_tp",
           "pipeline_apply", "shard_stacked_params",
           "stack_stage_params", "MixtureOfExperts",
           "moe_load_balancing_loss"]
