"""Compiled-step cost accounting (ISSUE 6 tentpole).

``mx.telemetry`` (ISSUE 2) counts host-side events; ``mx.profiler``
wraps ``jax.profiler``'s TensorBoard traces.  Neither can say which
HLOs eat the chip.  This subsystem is the missing device-cost layer --
the TPU-native rebirth of the reference's ``src/profiler/profiler.cc``
per-op stats, rebuilt on XLA's own cost model:

- Every compiled executable the framework dispatches (eager-jit cache,
  hybridize cache, ``Executor``, ``parallel.TrainStep``) is captured
  into a :class:`CostReport`: XLA's ``cost_analysis()`` totals (FLOPs,
  bytes accessed) + ``memory_analysis()`` (argument/output/temp HBM,
  peak estimate) + a per-HLO-**category** breakdown (conv/dot,
  collective, transpose-layout, elementwise/fusion, other) attributed
  by parsing the compiled HLO text, reconciled so categories sum
  exactly to the executable totals.
- An analytic roofline turns measured step time + CostReport into
  achieved-vs-peak compute and bandwidth per category, labeling each
  category compute- or memory-bound -- MFU decomposed.
- A lightweight always-available step timeline (host spans +
  transfer/donation events) exports as Chrome-trace JSON
  (``chrome://tracing`` / Perfetto) without TensorBoard.
- The ``mxprof`` CLI (``report`` / ``diff``) renders report artifacts
  and names the categories whose FLOPs/bytes/peak-HBM drifted between
  two runs -- the regression-attribution contract of ROADMAP item 2.

Enable with ``MXNET_TPU_PROFILING=1`` or ``mx.profiling.enable()``.
Disabled (the default), every hook is one module-flag check.  With
``MXNET_TPU_PROFILING_DIR`` set, reports are persisted there at exit
(and by ``save_reports()``).
"""
from __future__ import annotations

import os

__all__ = [
    "enable", "disable", "enabled", "reset",
    "capture_jit", "record_step", "reports", "combined_report",
    "save_reports", "report_for", "report_dir", "flops_per_step",
    "CATEGORIES",
]

# Hot-path gate: instrumented modules check this one module attribute
# (same contract as telemetry._ENABLED) and make zero calls when off.
_ENABLED = False

# HLO cost categories (docs/profiling.md); re-exported from hlo.py at
# first use -- kept literal here so importing the gate stays stdlib-only.
CATEGORIES = ("conv_dot", "collective", "transpose_layout",
              "elementwise_fusion", "other")

_atexit_armed = False


def enable():
    """Turn the capture hooks on (idempotent)."""
    global _ENABLED
    _ENABLED = True
    _arm_atexit()


def disable():
    """Turn the capture hooks off; captured reports are kept."""
    global _ENABLED
    _ENABLED = False


def enabled():
    return _ENABLED


def report_dir():
    """Report directory from ``MXNET_TPU_PROFILING_DIR`` (empty string
    when unset -- callers pass an explicit dir then)."""
    return os.environ.get("MXNET_TPU_PROFILING_DIR", "")


def _arm_atexit():
    """With a report dir configured, persist everything captured when
    the process exits (the JSONL-sink analog for cost reports)."""
    global _atexit_armed
    if _atexit_armed or not report_dir():
        return
    import atexit

    def _flush():
        try:
            if _ENABLED:
                save_reports()
        except Exception:
            pass
    atexit.register(_flush)
    _atexit_armed = True


# -- capture surface (called by the instrumented hot paths) ------------

def capture_jit(label, fn, args, key=None, kind="jit", **meta):
    """Register a jitted callable + example args for lazy cost
    analysis.  Dedupes on ``key``; the expensive lower+compile+parse
    happens at ``reports()`` / ``save_reports()`` time, never on the
    training hot path.  ``args`` are abstracted to ShapeDtypeStructs
    immediately, so no device buffer is kept alive."""
    from . import store
    store.register(key if key is not None else (label,), label, fn, args,
                   kind=kind, **meta)


def record_step(label, seconds, items=None):
    """Record one measured step wall time for ``label`` (feeds the
    roofline's achieved-vs-peak numbers)."""
    from . import store
    store.record_step(label, seconds, items=items)


def reports():
    """Materialize every pending capture and return the list of
    CostReport dicts (step stats + roofline attached where known)."""
    from . import store
    return store.reports()


def combined_report():
    """One combined report dict (steps + executables + category
    rollup) -- the artifact ``mxprof report``/``diff`` consume."""
    from . import store
    return store.combined()


def flops_per_step(label=None):
    """FLOPs of one dispatch of the labeled captured executable
    (default: the first train_step) -- the goodput ledger's
    window-flops source.  None when nothing matches."""
    from . import store
    return store.flops_per_step(label)


def save_reports(dirpath=None):
    """Write per-executable ``*.cost.json`` files plus the combined
    ``report.json`` under ``dirpath`` (default: the env report dir).
    Returns the combined report path."""
    from . import store
    return store.save(dirpath)


def reset():
    """Drop captured reports, pending specs, step times, and timeline
    events (test isolation)."""
    from . import store, timeline
    store.clear()
    timeline.clear()


def report_for(obj, label=None, step_time_s=None, items_per_step=None):
    """CostReport for an object exposing ``_last_call = (fn, args)``
    (``parallel.TrainStep`` does) or for a ``(fn, args)`` tuple.
    Synchronous -- used by bench.py to persist artifacts without the
    store.  Returns None when nothing was dispatched yet."""
    from . import cost, roofline
    last = obj if isinstance(obj, tuple) else getattr(obj, "_last_call",
                                                     None)
    if last is None:
        return None
    fn, args = last
    rep = cost.analyze_jit(fn, args, label=label or "train_step")
    if rep is not None and step_time_s:
        rep["step"] = {"count": 1, "mean_s": step_time_s,
                       "min_s": step_time_s, "max_s": step_time_s,
                       "total_s": step_time_s}
        rep["roofline"] = roofline.build(rep, step_time_s,
                                         items_per_step=items_per_step)
    return rep


# env arming (read directly, matching the package's != "0" convention;
# the typed registry view lives in mxnet_tpu/env.py).
# MXNET_TPU_SHARD_CHECK rides the same capture surface: the sharding
# sanitizer's collective-contract audit (analysis/sharding.py) reads
# registered executables from this store, so arming it arms capture.
if os.environ.get("MXNET_TPU_PROFILING", "0") != "0" or \
        os.environ.get("MXNET_TPU_SHARD_CHECK", "0") != "0":
    enable()
