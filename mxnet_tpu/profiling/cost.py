"""CostReport: XLA cost/memory analysis + per-category attribution.

One report per compiled executable, keyed by a fingerprint of the
post-optimization HLO (normalized: trace metadata and the module name
are stripped, so identical programs recompiled -- or retraced from a
fresh ``jax.jit`` of the same code -- fingerprint identically).

The per-category numbers are the ``hlo.py`` analytic estimates
RECONCILED against XLA's executable totals: each category is scaled by
``total/estimate`` and rounded, with the remainder pinned on the
largest category, so ``sum(categories[*].flops) == round(totals.flops)``
exactly (the ``mxprof report`` contract).  The raw estimates are kept
under ``estimates`` for debugging attribution drift.
"""
from __future__ import annotations

import hashlib
import re

from . import hlo

SCHEMA = "mxprof.cost_report.v1"

_NORM_METADATA = re.compile(r",?\s*metadata=\{[^}]*\}")
_NORM_MODULE = re.compile(r"^HloModule\s+\S+", re.MULTILINE)


def fingerprint(text):
    """Stable identity of a compiled program: sha256 of the HLO text
    with volatile parts (module name, source-location metadata)
    normalized away."""
    norm = _NORM_METADATA.sub("", text)
    norm = _NORM_MODULE.sub("HloModule <norm>", norm)
    return hashlib.sha256(norm.encode()).hexdigest()[:16]


def _reconcile(cats, total, key):
    """Scale category ``key`` estimates so they sum exactly to
    ``total`` (int).  Zero-estimate cases dump the whole total on
    'other' -- visible, not hidden."""
    total = int(round(total))
    est = {c: cats[c][key] for c in hlo.CATEGORIES}
    est_sum = sum(est.values())
    if total <= 0:
        return {c: 0 for c in hlo.CATEGORIES}
    if est_sum <= 0:
        out = {c: 0 for c in hlo.CATEGORIES}
        out["other"] = total
        return out
    out = {c: int(round(v * total / est_sum)) for c, v in est.items()}
    drift = total - sum(out.values())
    out[max(out, key=out.get)] += drift
    return out


def analyze_compiled(compiled, label="executable", kind="jit", **meta):
    """Build a CostReport dict from a ``jax.stages.Compiled``."""
    import jax

    totals = {"flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = dict(ca or {})
        totals["flops"] = float(ca.get("flops", 0.0))
        totals["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
        totals["transcendentals"] = float(ca.get("transcendentals", 0.0))
    except Exception:
        ca = {}

    memory = {}
    try:
        ms = compiled.memory_analysis()
        memory = {
            "argument_bytes": int(ms.argument_size_in_bytes),
            "output_bytes": int(ms.output_size_in_bytes),
            "temp_bytes": int(ms.temp_size_in_bytes),
            "alias_bytes": int(ms.alias_size_in_bytes),
            "generated_code_bytes": int(ms.generated_code_size_in_bytes),
        }
        # aliased (donated) buffers are counted in both argument and
        # output totals but exist once on the chip
        memory["peak_hbm_bytes"] = max(
            0, memory["argument_bytes"] + memory["output_bytes"]
            + memory["temp_bytes"] - memory["alias_bytes"])
    except Exception:
        memory = {"argument_bytes": 0, "output_bytes": 0, "temp_bytes": 0,
                  "alias_bytes": 0, "generated_code_bytes": 0,
                  "peak_hbm_bytes": 0}

    text = ""
    try:
        text = compiled.as_text()
    except Exception:
        pass
    attributed = hlo.analyze(text) if text else \
        {"categories": {c: {"flops": 0, "bytes": 0, "instructions": 0}
                        for c in hlo.CATEGORIES}, "provenance": []}
    est = attributed["categories"]
    # no XLA totals (some backends): the analytic estimate IS the total
    if not totals["flops"]:
        totals["flops"] = float(sum(c["flops"] for c in est.values()))
    if not totals["bytes_accessed"]:
        totals["bytes_accessed"] = float(sum(c["bytes"]
                                             for c in est.values()))

    flops_rec = _reconcile(est, totals["flops"], "flops")
    bytes_rec = _reconcile(est, totals["bytes_accessed"], "bytes")
    tf, tb = max(totals["flops"], 1.0), max(totals["bytes_accessed"], 1.0)
    categories = {
        c: {"flops": flops_rec[c], "bytes": bytes_rec[c],
            "instructions": est[c]["instructions"],
            "flops_share": round(flops_rec[c] / tf, 4),
            "bytes_share": round(bytes_rec[c] / tb, 4)}
        for c in hlo.CATEGORIES}

    try:
        device = jax.devices()[0].device_kind
        backend = jax.default_backend()
    except Exception:
        device, backend = "unknown", "unknown"

    return {
        "schema": SCHEMA,
        "label": label,
        "kind": kind,
        "fingerprint": fingerprint(text) if text else "",
        "device": device,
        "backend": backend,
        "totals": totals,
        "memory": memory,
        "categories": categories,
        "estimates": {c: {"flops": est[c]["flops"],
                          "bytes": est[c]["bytes"]}
                      for c in hlo.CATEGORIES},
        "provenance": attributed["provenance"],
        "step": None,
        "roofline": None,
        **({"meta": meta} if meta else {}),
    }


def analyze_jit(fn, args, label="executable", kind="jit", **meta):
    """Lower+compile ``fn`` on abstracted ``args`` and analyze.  Hits
    the jit executable cache when ``fn`` was already dispatched on the
    same avals, so this never doubles real compile work.  Returns None
    when the function cannot be lowered (e.g. args gone stale)."""
    import jax

    def _abstract(x):
        if hasattr(x, "shape") and hasattr(x, "dtype") and \
                not isinstance(x, jax.ShapeDtypeStruct):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x
    try:
        specs = jax.tree_util.tree_map(_abstract, args)
        compiled = fn.lower(*specs).compile()
    except Exception:
        return None
    return analyze_compiled(compiled, label=label, kind=kind, **meta)
