"""``mxprof`` -- render and diff compiled-step cost reports.

Contract mirrors mxlint/mxtelemetry: exit 0 on success, 1 when the
gate fails (no reports found; drift detected by ``diff``), 2 on usage
or unreadable-input errors.  ``--json`` keeps every mode
machine-readable.

::

    mxprof report --dir mxprof_reports            # human tables
    mxprof report --dir mxprof_reports --json     # combined dict
    mxprof diff old/report.json new/report.json   # exit 1 + named
                                                  # categories on drift
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from .hlo import CATEGORIES
from .store import COMBINED_NAME, COMBINED_SCHEMA
from .cost import SCHEMA as REPORT_SCHEMA

__all__ = ["main", "load_report", "diff_reports"]

# fields compared per category and per report by ``diff``
_DIFF_TOL_DEFAULT = 0.02


def _fmt_flops(v):
    for unit, div in (("PFLOP", 1e15), ("TFLOP", 1e12), ("GFLOP", 1e9),
                      ("MFLOP", 1e6), ("kFLOP", 1e3)):
        if v >= div:
            return "%.2f %s" % (v / div, unit)
    return "%.0f FLOP" % v


def _fmt_bytes(v):
    for unit, div in (("GB", 1e9), ("MB", 1e6), ("kB", 1e3)):
        if v >= div:
            return "%.2f %s" % (v / div, unit)
    return "%d B" % v


def load_report(path):
    """Load a combined report or a single CostReport; both normalize
    to the combined shape so ``report``/``diff`` handle either."""
    with open(path) as f:
        data = json.load(f)
    if data.get("schema") == COMBINED_SCHEMA:
        return data
    if data.get("schema") == REPORT_SCHEMA:
        return {
            "schema": COMBINED_SCHEMA,
            "steps": ({data["label"]: data["step"]} if data.get("step")
                      else {}),
            "executables": [data],
            "totals": {"flops": data["totals"]["flops"],
                       "bytes_accessed": data["totals"]["bytes_accessed"],
                       "peak_hbm_bytes": data["memory"]["peak_hbm_bytes"]},
            "categories": {c: {"flops": v["flops"], "bytes": v["bytes"],
                               "instructions": v["instructions"]}
                           for c, v in data["categories"].items()},
        }
    raise ValueError("%s: unrecognized schema %r"
                     % (path, data.get("schema")))


def _collect(paths, dirpath):
    """Resolve report sources into one combined dict."""
    if paths:
        reps = [load_report(p) for p in paths]
        if len(reps) == 1:
            return reps[0]
        merged = {"schema": COMBINED_SCHEMA, "steps": {},
                  "executables": [], "totals": {"flops": 0.0,
                                                "bytes_accessed": 0.0,
                                                "peak_hbm_bytes": 0},
                  "categories": {}}
        for r in reps:
            merged["steps"].update(r["steps"])
            merged["executables"].extend(r["executables"])
            merged["totals"]["flops"] += r["totals"]["flops"]
            merged["totals"]["bytes_accessed"] += \
                r["totals"]["bytes_accessed"]
            # peak HBM merges as MAX, not sum: reports come from
            # separate dispatches whose live sets never coexist, so the
            # combined peak is the worst single program's peak (the
            # same convention as store.combined() and the memory
            # auditor's same-label merge; asserted by
            # test_memory.test_mxprof_merge_peak_is_max)
            merged["totals"]["peak_hbm_bytes"] = max(
                merged["totals"]["peak_hbm_bytes"],
                r["totals"]["peak_hbm_bytes"])
            for c, v in r["categories"].items():
                agg = merged["categories"].setdefault(
                    c, {"flops": 0, "bytes": 0, "instructions": 0})
                for k in agg:
                    agg[k] += v.get(k, 0)
        return merged
    comb = os.path.join(dirpath, COMBINED_NAME)
    if os.path.isfile(comb):
        return load_report(comb)
    singles = sorted(glob.glob(os.path.join(dirpath, "*.cost.json")))
    if singles:
        return _collect(singles, dirpath)
    return None


def _render_report(comb):
    lines = ["mxprof report: %d executable(s), %d step label(s)"
             % (len(comb["executables"]), len(comb["steps"]))]
    if comb["steps"]:
        lines.append("")
        lines.append("steps:")
        for label, st in sorted(comb["steps"].items()):
            if not st or not st.get("count"):
                continue
            lines.append("  %-36s count %-5d mean %8.2fms  "
                         "min %.2fms max %.2fms"
                         % (label, st["count"],
                            1e3 * st["total_s"] / st["count"],
                            1e3 * (st["min_s"] or 0),
                            1e3 * (st["max_s"] or 0)))
    lines.append("")
    lines.append("executables:")
    lines.append("  %-36s %-16s %12s %12s %12s  %s"
                 % ("label", "fingerprint", "flops", "bytes",
                    "peak HBM", "top category"))
    for rep in comb["executables"]:
        top = max(rep["categories"],
                  key=lambda c: rep["categories"][c]["flops"])
        bound = ""
        rl = rep.get("roofline")
        if rl and top in rl["categories"]:
            bound = " (%s-bound%s)" % (
                rl["categories"][top]["bound"],
                ", peaks assumed" if rl["peaks_assumed"] else "")
        lines.append("  %-36s %-16s %12s %12s %12s  %s%s"
                     % (rep["label"][:36], rep["fingerprint"],
                        _fmt_flops(rep["totals"]["flops"]),
                        _fmt_bytes(rep["totals"]["bytes_accessed"]),
                        _fmt_bytes(rep["memory"]["peak_hbm_bytes"]),
                        top, bound))
        if rl:
            lines.append("    roofline: mfu %.3f, bw util %.3f, "
                         "floor %.2fms vs measured %.2fms"
                         % (rl["mfu"], rl["bandwidth_util"],
                            1e3 * rl["floor_step_s"],
                            1e3 * rl["step_time_s"]))
            for cat in CATEGORIES:
                cv = rl["categories"].get(cat)
                if cv:
                    lines.append("      %-20s %7s-bound  "
                                 "time share %5.1f%%"
                                 % (cat, cv["bound"],
                                    100 * cv["time_share"]))
    lines.append("")
    lines.append("totals: flops %s  bytes %s  peak HBM %s (max over "
                 "executables; peaks of separate dispatches never add)"
                 % (_fmt_flops(comb["totals"]["flops"]),
                    _fmt_bytes(comb["totals"]["bytes_accessed"]),
                    _fmt_bytes(comb["totals"]["peak_hbm_bytes"])))
    if comb["categories"]:
        tf = max(comb["totals"]["flops"], 1.0)
        tb = max(comb["totals"]["bytes_accessed"], 1.0)
        lines.append("")
        lines.append("categories (rollup over executables):")
        for cat in CATEGORIES:
            v = comb["categories"].get(cat)
            if not v:
                continue
            lines.append("  %-20s flops %12s (%5.1f%%)  "
                         "bytes %12s (%5.1f%%)  %d instr"
                         % (cat, _fmt_flops(v["flops"]),
                            100 * v["flops"] / tf,
                            _fmt_bytes(v["bytes"]),
                            100 * v["bytes"] / tb,
                            v["instructions"]))
    return "\n".join(lines)


def _rel(old, new):
    return abs(new - old) / max(abs(old), 1.0)


def diff_reports(old, new, tol=_DIFF_TOL_DEFAULT):
    """Compare two combined reports.  Returns a list of drift dicts
    ``{"scope", "category"/"field", "old", "new", "rel"}`` -- empty
    when nothing moved beyond ``tol`` (relative)."""
    drifts = []

    def check(scope, field, o, n):
        r = _rel(o, n)
        if r > tol:
            drifts.append({"scope": scope, "field": field,
                           "old": o, "new": n, "rel": round(r, 4)})

    for cat in CATEGORIES:
        ov = old["categories"].get(cat, {"flops": 0, "bytes": 0})
        nv = new["categories"].get(cat, {"flops": 0, "bytes": 0})
        check("category:" + cat, "flops", ov["flops"], nv["flops"])
        check("category:" + cat, "bytes", ov["bytes"], nv["bytes"])
    check("totals", "flops", old["totals"]["flops"],
          new["totals"]["flops"])
    check("totals", "bytes_accessed", old["totals"]["bytes_accessed"],
          new["totals"]["bytes_accessed"])
    check("totals", "peak_hbm_bytes", old["totals"]["peak_hbm_bytes"],
          new["totals"]["peak_hbm_bytes"])
    # per-label peak HBM: the "one executable regressed" case the
    # rollup can mask when another shrank.  Labels repeat (two Dense
    # layers are two `eager:FullyConnected` programs), so pair by
    # position WITHIN each label group -- a report diffed against
    # itself must always align every executable with itself.
    def by_label(reps):
        groups = {}
        for r in reps:
            groups.setdefault(r["label"], []).append(r)
        return groups
    old_groups = by_label(old["executables"])
    for label, news in by_label(new["executables"]).items():
        for i, rep in enumerate(news):
            olds = old_groups.get(label, [])
            if i >= len(olds):
                continue
            check("executable:" + label, "peak_hbm_bytes",
                  olds[i]["memory"]["peak_hbm_bytes"],
                  rep["memory"]["peak_hbm_bytes"])
    return drifts


def _render_diff(drifts, old_path, new_path, tol):
    if not drifts:
        return "mxprof diff: no drift beyond %.1f%% between %s and %s" \
            % (100 * tol, old_path, new_path)
    lines = ["mxprof diff: %d drift(s) beyond %.1f%% (%s -> %s)"
             % (len(drifts), 100 * tol, old_path, new_path)]
    cats = sorted({d["scope"].split(":", 1)[1] for d in drifts
                   if d["scope"].startswith("category:")})
    if cats:
        lines.append("  drifted categories: %s" % ", ".join(cats))
    for d in drifts:
        lines.append("  %-28s %-16s %15.4g -> %-15.4g (%+.1f%%)"
                     % (d["scope"], d["field"], d["old"], d["new"],
                        100 * (d["new"] - d["old"])
                        / max(abs(d["old"]), 1.0)))
    return "\n".join(lines)


def _build_parser():
    ap = argparse.ArgumentParser(
        prog="mxprof",
        description="Compiled-step cost accounting (docs/profiling.md).")
    sub = ap.add_subparsers(dest="cmd")
    rp = sub.add_parser("report", help="render cost-report artifacts")
    rp.add_argument("paths", nargs="*",
                    help="report.json / *.cost.json files (default: "
                         "--dir discovery)")
    rp.add_argument("--dir", default=None,
                    help="report directory (default: "
                         "$MXNET_TPU_PROFILING_DIR or mxprof_reports)")
    rp.add_argument("--json", dest="as_json", action="store_true")
    dp = sub.add_parser("diff", help="compare two report artifacts; "
                                     "exit 1 naming drifted categories")
    dp.add_argument("old")
    dp.add_argument("new")
    dp.add_argument("--tol", type=float, default=_DIFF_TOL_DEFAULT,
                    help="relative drift tolerance (default %g)"
                         % _DIFF_TOL_DEFAULT)
    dp.add_argument("--json", dest="as_json", action="store_true")
    return ap


def main(argv=None) -> int:
    try:
        return _main(argv)
    except BrokenPipeError:
        # downstream pager/head closed early: success, not a stack
        # trace (same contract as mxtelemetry); devnull-dup so the
        # interpreter's final stdout flush cannot re-raise
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


def _main(argv=None) -> int:
    ap = _build_parser()
    args = ap.parse_args(argv)
    if args.cmd == "report":
        dirpath = args.dir
        if dirpath is None:
            from . import report_dir
            dirpath = report_dir() or "mxprof_reports"
        try:
            comb = _collect(args.paths, dirpath)
        except (OSError, ValueError, KeyError) as e:
            print("mxprof report: cannot load reports: %s" % e,
                  file=sys.stderr)
            return 2
        if comb is None or not comb["executables"]:
            print("mxprof report: no cost reports under %r (run with "
                  "MXNET_TPU_PROFILING=1 and save_reports())"
                  % dirpath, file=sys.stderr)
            return 1
        print(json.dumps(comb, indent=1, sort_keys=True)
              if args.as_json else _render_report(comb))
        return 0
    if args.cmd == "diff":
        try:
            old = load_report(args.old)
            new = load_report(args.new)
        except (OSError, ValueError, KeyError) as e:
            print("mxprof diff: cannot load reports: %s" % e,
                  file=sys.stderr)
            return 2
        drifts = diff_reports(old, new, tol=args.tol)
        if args.as_json:
            print(json.dumps({"tol": args.tol, "drifts": drifts},
                             indent=1, sort_keys=True))
        else:
            print(_render_diff(drifts, args.old, args.new, args.tol))
        return 1 if drifts else 0
    ap.print_usage()
    return 2


if __name__ == "__main__":
    sys.exit(main())
