"""Optimized-HLO text parser: per-instruction cost attribution.

XLA's ``compiled.cost_analysis()`` reports executable TOTALS only.  To
say *which* HLOs eat them, this module parses ``compiled.as_text()``
(the scheduled post-optimization module) and attributes an analytic
FLOP/byte estimate to every instruction, bucketed into five categories:

==================  ==================================================
category            opcodes
==================  ==================================================
conv_dot            convolution, dot, matmul/gemm/conv custom-calls --
                    the MXU work
collective          all-reduce/-gather/-to-all, reduce-scatter,
                    collective-permute, send/recv -- the ICI work
transpose_layout    transpose, copy, bitcast, reshape, pad, slice,
                    concatenate, gather, broadcast -- pure data
                    movement (the NHWC/NCHW tax lives here)
elementwise_fusion  arithmetic/compare/select/reduce/rng -- what XLA
                    fuses around the big ops
other               scatter, sort, fft, custom-calls, anything unknown
==================  ==================================================

Attribution rules:

- Fused computations' *instructions* carry the FLOPs (fusion bodies
  never touch HBM); the fusion *call site* carries the bytes (its
  operands + output are the real memory traffic), attributed to the
  body's dominant category.
- ``while`` bodies are counted once (per-iteration cost; trip counts
  are not in the HLO text) -- scan-based programs report their loop
  body, matching ``TrainStep.run_steps``'s documented convention.
- ``to_apply`` regions of reduce/scatter/sort are per-element lambdas
  and are not walked (the caller instruction already carries the cost).

The estimates are RECONCILED against the executable totals in
``cost.py`` so per-category numbers sum exactly to what XLA measured;
the raw analytic estimates are preserved alongside.
"""
from __future__ import annotations

import re

CATEGORIES = ("conv_dot", "collective", "transpose_layout",
              "elementwise_fusion", "other")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "token": 0, "opaque": 0,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,<=\s]*)\]")
_INSTR_RE = re.compile(r"^\s+(?:ROOT\s+)?%[\w.\-]+\s*=\s*(.*)$")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%([\w.\-]+)\s*\(.*\)\s*->")
_METADATA_RE = re.compile(r",?\s*metadata=\{[^}]*\}")
_STRING_RE = re.compile(r'"[^"]*"')
_OPNAME_RE = re.compile(r'op_name="([^"]+)"')
_CALLS_RE = re.compile(r"\bcalls=%([\w.\-]+)")
_BODY_RE = re.compile(r"\bbody=%([\w.\-]+)")
_COND_RE = re.compile(r"\bcondition=%([\w.\-]+)")
_BRANCHES_RE = re.compile(r"\bbranch_computations=\{([^}]*)\}")
_TRUE_RE = re.compile(r"\btrue_computation=%([\w.\-]+)")
_FALSE_RE = re.compile(r"\bfalse_computation=%([\w.\-]+)")
_TOAPPLY_RE = re.compile(r"\bto_apply=%([\w.\-]+)")
_LHS_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,\s]*)\}")
_WINDOW_SIZE_RE = re.compile(r"window=\{[^}]*?size=([0-9x]+)")
_DIM_LABELS_RE = re.compile(r"dim_labels=\w+_(\w+)->")
_CUSTOM_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')

_CONV_DOT = {"convolution", "dot"}
_COLLECTIVE = {
    "all-reduce", "all-reduce-start", "all-reduce-done",
    "all-gather", "all-gather-start", "all-gather-done",
    "all-to-all", "reduce-scatter", "collective-permute",
    "collective-permute-start", "collective-permute-done",
    "collective-broadcast", "send", "send-done", "recv", "recv-done",
    "partition-id", "replica-id",
}
_LAYOUT = {
    "transpose", "copy", "copy-start", "copy-done", "bitcast",
    "bitcast-convert", "reshape", "dynamic-reshape", "pad", "slice",
    "dynamic-slice", "dynamic-update-slice", "concatenate", "reverse",
    "broadcast", "gather",
}
_OTHER = {"scatter", "sort", "fft", "triangular-solve", "cholesky",
          "custom-call", "infeed", "outfeed", "domain", "optimization-barrier"}
# zero-cost bookkeeping, skipped entirely
_SKIP = {"parameter", "constant", "tuple", "get-tuple-element",
         "after-all", "add-dependency"}
# control-flow call sites: cost lives in the callee computations
_CONTROL = {"fusion", "while", "conditional", "call", "async-start",
            "async-update", "async-done"}

# estimated-FLOPs-per-element > 1 for transcendentals would double-count
# against XLA's separate 'transcendentals' tally; keep 1/elem everywhere.


def _prod(dims):
    out = 1
    for d in dims:
        out *= d
    return out


def _parse_shapes(text):
    """All ``dtype[dims]`` arrays in ``text`` as (dtype, dims-tuple)."""
    out = []
    for dt, dims in _SHAPE_RE.findall(text):
        if dt not in _DTYPE_BYTES:
            continue
        dims = dims.replace("<=", "").strip()
        try:
            shape = tuple(int(d) for d in dims.split(",") if d.strip()) \
                if dims else ()
        except ValueError:
            continue
        out.append((dt, shape))
    return out


def _nbytes(shapes):
    return sum(_DTYPE_BYTES.get(dt, 4) * _prod(dims)
               for dt, dims in shapes)


class Instr:
    __slots__ = ("opcode", "out_shapes", "operand_shapes", "attrs",
                 "op_name")

    def __init__(self, opcode, out_shapes, operand_shapes, attrs,
                 op_name):
        self.opcode = opcode
        self.out_shapes = out_shapes
        self.operand_shapes = operand_shapes
        self.attrs = attrs
        self.op_name = op_name


def parse_module(text):
    """Parse the HLO text into ``(entry_name, {comp_name: [Instr]},
    {comp_name: callee refs})``."""
    comps = {}
    refs = {}
    entry = None
    cur = None
    for line in text.splitlines():
        m = _COMP_RE.match(line)
        if m and line.rstrip().endswith("{"):
            cur = m.group(1)
            comps[cur] = []
            refs[cur] = []
            if line.startswith("ENTRY"):
                entry = cur
            continue
        if line.startswith("}"):
            cur = None
            continue
        if cur is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        rhs = m.group(1)
        op_name_m = _OPNAME_RE.search(rhs)
        op_name = op_name_m.group(1) if op_name_m else None
        clean = _METADATA_RE.sub("", rhs)
        clean_noquote = _STRING_RE.sub('""', clean)
        # output type: a tuple "(...)" or a single array shape
        if clean_noquote.startswith("("):
            depth = 0
            for i, ch in enumerate(clean_noquote):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        break
            out_txt, rest = clean_noquote[:i + 1], clean_noquote[i + 1:]
        else:
            sm = _SHAPE_RE.match(clean_noquote)
            if sm is None:
                continue
            j = sm.end()
            # optional layout suffix {1,0}
            if j < len(clean_noquote) and clean_noquote[j] == "{":
                j = clean_noquote.index("}", j) + 1
            out_txt, rest = clean_noquote[:j], clean_noquote[j:]
        rest = rest.strip()
        om = re.match(r"([\w\-]+)\(", rest)
        if om is None:
            continue
        opcode = om.group(1)
        # operand section: the opcode's balanced parens
        start = om.end() - 1
        depth = 0
        end = len(rest)
        for i in range(start, len(rest)):
            if rest[i] == "(":
                depth += 1
            elif rest[i] == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        operands = rest[start + 1:end]
        attrs = rest[end + 1:]
        instr = Instr(opcode, _parse_shapes(out_txt),
                      _parse_shapes(operands), attrs, op_name)
        comps[cur].append(instr)
        for rx in (_CALLS_RE, _BODY_RE, _COND_RE, _TRUE_RE, _FALSE_RE):
            refs[cur].extend(rx.findall(clean_noquote))
        bm = _BRANCHES_RE.search(clean_noquote)
        if bm:
            refs[cur].extend(n.strip().lstrip("%")
                             for n in bm.group(1).split(","))
        if opcode == "call":
            refs[cur].extend(_TOAPPLY_RE.findall(clean_noquote))
    return entry, comps, refs


def category_of(instr):
    op = instr.opcode
    if op in _CONV_DOT:
        return "conv_dot"
    if op == "custom-call":
        tm = _CUSTOM_TARGET_RE.search(instr.attrs)
        t = (tm.group(1) if tm else "").lower()
        if any(k in t for k in ("conv", "dot", "matmul", "gemm")):
            return "conv_dot"
        if any(k in t for k in ("allreduce", "all_reduce", "allgather",
                                "all_gather", "alltoall",
                                "reducescatter", "reduce_scatter",
                                "permute")):
            return "collective"
        return "other"
    if op in _COLLECTIVE:
        return "collective"
    if op in _LAYOUT:
        return "transpose_layout"
    if op in _OTHER:
        return "other"
    return "elementwise_fusion"


def _flops_of(instr):
    out_elems = _prod(instr.out_shapes[0][1]) if instr.out_shapes else 0
    op = instr.opcode
    if op == "dot":
        k = 1
        cm = _LHS_CONTRACT_RE.search(instr.attrs)
        if cm and instr.operand_shapes:
            lhs = instr.operand_shapes[0][1]
            for d in cm.group(1).split(","):
                d = d.strip()
                if d and int(d) < len(lhs):
                    k *= lhs[int(d)]
        return 2 * out_elems * k
    if op == "convolution":
        win = 1
        wm = _WINDOW_SIZE_RE.search(instr.attrs)
        if wm:
            for d in wm.group(1).split("x"):
                win *= int(d)
        in_ch = 1
        dm = _DIM_LABELS_RE.search(instr.attrs)
        if dm and len(instr.operand_shapes) > 1:
            rhs_labels = dm.group(1)
            if "i" in rhs_labels:
                idx = rhs_labels.index("i")
                rhs = instr.operand_shapes[1][1]
                if idx < len(rhs):
                    in_ch = rhs[idx]
        return 2 * out_elems * win * in_ch
    if op in _LAYOUT or op in _SKIP or op in _CONTROL or op in _COLLECTIVE:
        return 0
    if op in ("reduce", "reduce-window", "select-and-scatter"):
        return _prod(instr.operand_shapes[0][1]) \
            if instr.operand_shapes else out_elems
    if op == "custom-call":
        return 0   # opaque; the reconciliation residual covers it
    return out_elems


def analyze(text, top=12):
    """Walk the compiled module and return::

        {"categories": {cat: {"flops", "bytes", "instructions"}},
         "provenance": [{"op_name", "category", "flops"}, ...]}

    ``provenance`` is the top FLOP-consuming framework scopes, taken
    from the ``op_name`` trace metadata (the scope names the executors
    and ``profiler.scope`` emit during tracing).
    """
    entry, comps, refs = parse_module(text)
    cats = {c: {"flops": 0, "bytes": 0, "instructions": 0}
            for c in CATEGORIES}
    prov = {}

    def body_cost(name, seen):
        """Aggregate a computation's instruction costs; recursing into
        fusion/control callees.  ``in_fusion`` bodies contribute flops
        only -- their HBM traffic is the call site's."""
        if name not in comps or name in seen:
            return
        seen.add(name)
        for ins in comps[name]:
            walk_instr(ins, seen, in_fusion=True)

    def fusion_body_summary(name):
        """(dominant category, flops per cat, instr count per cat) of a
        fused computation, for attributing the call site's bytes."""
        fl = {c: 0 for c in CATEGORIES}
        n = {c: 0 for c in CATEGORIES}

        def acc(nm, seen):
            if nm not in comps or nm in seen:
                return
            seen.add(nm)
            for ins in comps[nm]:
                if ins.opcode in _SKIP:
                    continue
                if ins.opcode == "fusion":
                    for callee in _CALLS_RE.findall(ins.attrs):
                        acc(callee, seen)
                    continue
                c = category_of(ins)
                fl[c] += _flops_of(ins)
                n[c] += 1
        acc(name, set())
        by_flops = max(fl, key=lambda c: fl[c])
        if fl[by_flops] > 0:
            return by_flops
        n["elementwise_fusion"] += 0  # stable tie-break below
        priority = {"conv_dot": 4, "collective": 3, "transpose_layout": 2,
                    "elementwise_fusion": 1, "other": 0}
        return max(CATEGORIES, key=lambda c: (n[c], priority[c]))

    def record(cat, flops, nbytes, ins):
        cats[cat]["flops"] += flops
        cats[cat]["bytes"] += nbytes
        cats[cat]["instructions"] += 1
        if ins.op_name and flops:
            key = ins.op_name
            ent = prov.setdefault(key, {"op_name": key, "category": cat,
                                        "flops": 0})
            ent["flops"] += flops

    def walk_instr(ins, seen, in_fusion=False):
        op = ins.opcode
        if op in _SKIP:
            return
        if op == "fusion":
            callees = _CALLS_RE.findall(ins.attrs)
            for callee in callees:
                body_cost(callee, seen)
            if not in_fusion:
                cat = fusion_body_summary(callees[0]) if callees \
                    else "elementwise_fusion"
                nbytes = _nbytes(ins.operand_shapes) + \
                    _nbytes(ins.out_shapes)
                cats[cat]["bytes"] += nbytes
            return
        if op in ("while", "conditional", "call") or \
                op.startswith("async-"):
            text_refs = []
            for rx in (_BODY_RE, _COND_RE, _TRUE_RE, _FALSE_RE,
                       _CALLS_RE, _TOAPPLY_RE):
                text_refs.extend(rx.findall(ins.attrs))
            bm = _BRANCHES_RE.search(ins.attrs)
            if bm:
                text_refs.extend(n.strip().lstrip("%")
                                 for n in bm.group(1).split(","))
            for callee in text_refs:
                walk_comp(callee, seen)
            return
        cat = category_of(ins)
        nbytes = 0 if in_fusion else \
            _nbytes(ins.operand_shapes) + _nbytes(ins.out_shapes)
        record(cat, _flops_of(ins), nbytes, ins)

    def walk_comp(name, seen):
        """Top-level walk: instructions here DO touch HBM."""
        if name not in comps or name in seen:
            return
        seen.add(name)
        for ins in comps[name]:
            walk_instr(ins, seen, in_fusion=False)

    if entry is not None:
        walk_comp(entry, set())
    provenance = sorted(prov.values(), key=lambda e: -e["flops"])[:top]
    return {"categories": cats, "provenance": provenance}
