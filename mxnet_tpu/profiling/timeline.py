"""Always-available step timeline -> Chrome-trace JSON.

``jax.profiler`` produces a TensorBoard-shaped trace you must load into
the profile plugin.  This is the lightweight complement: host-side
spans (train steps, compiles, feed staging, user ``profiler.scope``
regions) and instant events (buffer donation, markers) in a bounded
in-memory ring, exported as Chrome trace-event JSON that loads straight
into ``chrome://tracing`` or Perfetto -- no TensorBoard, no device
hooks, cheap enough to leave on for a whole run.

Recording only happens while ``mx.profiling`` is enabled; every hook
site is guarded by the module flag, so the off cost is one check.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from .. import sync as _sync

# bounded ring: a multi-hour run cannot grow host memory unboundedly
_MAX_EVENTS = 100_000

_lock = _sync.Lock(name="profiling.timeline")
_events = []
_dropped = 0
# timeline epoch = the perf_counter clock's own zero, so spans timed
# before this module's (lazy) import still land at positive offsets
_t0 = 0.0


def _ts():
    """Microseconds on the perf_counter clock (chrome trace 'ts')."""
    return (time.perf_counter() - _t0) * 1e6


def record(name, t_start, duration_s, args=None):
    """Record one complete span (begin ``t_start`` seconds on the
    perf_counter clock, lasting ``duration_s``)."""
    global _dropped
    ev = {"name": name, "ph": "X",
          "ts": (t_start - _t0) * 1e6,
          "dur": duration_s * 1e6,
          "pid": os.getpid(), "tid": threading.get_ident()}
    if args:
        ev["args"] = args
    with _lock:
        if len(_events) >= _MAX_EVENTS:
            del _events[:_MAX_EVENTS // 10]
            _dropped += _MAX_EVENTS // 10
        _events.append(ev)


def instant(name, args=None):
    """Record an instant event (chrome 'i' phase)."""
    global _dropped
    ev = {"name": name, "ph": "i", "ts": _ts(), "s": "t",
          "pid": os.getpid(), "tid": threading.get_ident()}
    if args:
        ev["args"] = args
    with _lock:
        if len(_events) >= _MAX_EVENTS:
            del _events[:_MAX_EVENTS // 10]
            _dropped += _MAX_EVENTS // 10
        _events.append(ev)


@contextlib.contextmanager
def span(name, **args):
    """``with timeline.span("phase"): ...`` -- records on exit."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record(name, t0, time.perf_counter() - t0, args or None)


def events():
    with _lock:
        return list(_events)


def dropped():
    return _dropped


def clear():
    global _dropped
    with _lock:
        del _events[:]
        _dropped = 0


def export_chrome_trace(path=None):
    """Chrome trace-event JSON of everything recorded.  Written to
    ``path`` when given; the dict is returned either way."""
    with _lock:
        evs = list(_events)
        ndropped = _dropped
    trace = {"traceEvents": evs, "displayTimeUnit": "ms",
             "otherData": {"producer": "mxnet_tpu.profiling.timeline",
                           "dropped_events": ndropped}}
    if path:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace
