"""Analytic roofline: measured step time x CostReport -> bound labels.

Given a CostReport and a measured step time, computes achieved FLOP/s
and bytes/s against the device's peak compute and HBM bandwidth, and
labels every HLO category compute- or memory-bound by comparing its
arithmetic intensity (FLOPs per byte moved) with the device's ridge
point ``peak_flops / peak_bandwidth``.  This is how an aggregate MFU
number decomposes into "the convs are compute-bound at X%, the
layout ops are pure bandwidth": the ceiling analysis ROADMAP item 2
asks for.

Peaks come from a device-kind table (TPU generations) or conservative
assumed defaults (CPU/dev boxes) -- ``peaks_assumed`` in the output
says which, so a CI roofline is never mistaken for chip truth.
"""
from __future__ import annotations

# (peak bf16 FLOP/s, peak HBM bytes/s) by device-kind prefix.  Sources:
# published TPU spec sheets; the bench's MFU table uses the same FLOPs.
_DEVICE_PEAKS = (
    ("TPU v5 lite", 197e12, 819e9),
    ("TPU v5e", 197e12, 819e9),
    ("TPU v5", 459e12, 2765e9),
    ("TPU v4", 275e12, 1228e9),
    ("TPU v3", 123e12, 900e9),
    ("TPU v2", 45e12, 700e9),
)

# dev-box fallback so the roofline SECTION always renders (CI runs on
# CPU); flagged assumed=True and sized for a generic server core
_ASSUMED_PEAKS = (5e11, 5e10)


def device_peaks(device_kind=None):
    """(peak_flops, peak_bytes_per_s, assumed) for the current (or
    named) device kind."""
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = ""
    for prefix, fl, bw in _DEVICE_PEAKS:
        if device_kind.startswith(prefix):
            return fl, bw, False
    return _ASSUMED_PEAKS[0], _ASSUMED_PEAKS[1], True


def build(report, step_time_s, peak_flops=None, peak_bytes_per_s=None,
          items_per_step=None):
    """Roofline section dict for ``report`` at ``step_time_s``."""
    fl, bw, assumed = device_peaks(report.get("device"))
    if peak_flops is not None:
        fl, assumed = peak_flops, False
    if peak_bytes_per_s is not None:
        bw = peak_bytes_per_s
    step_time_s = max(float(step_time_s), 1e-12)
    tot_f = report["totals"]["flops"]
    tot_b = report["totals"]["bytes_accessed"]
    achieved_f = tot_f / step_time_s
    achieved_b = tot_b / step_time_s
    ridge = fl / bw
    cats = {}
    time_est = {}
    for name, c in report["categories"].items():
        f, b = c["flops"], c["bytes"]
        if f == 0 and b == 0:
            continue
        intensity = (f / b) if b else float("inf")
        bound = "compute" if intensity >= ridge else "memory"
        # the category's floor time under the roofline model: whichever
        # wall (compute or bandwidth) it hits first
        time_est[name] = max(f / fl, b / bw)
        cats[name] = {"intensity": round(intensity, 3)
                      if intensity != float("inf") else None,
                      "bound": bound}
    t_total = sum(time_est.values()) or 1.0
    for name, t in time_est.items():
        cats[name]["time_share"] = round(t / t_total, 4)
        cats[name]["floor_s"] = round(t, 9)
    out = {
        "step_time_s": step_time_s,
        "peak_flops": fl,
        "peak_bytes_per_s": bw,
        "peaks_assumed": assumed,
        "ridge_intensity": round(ridge, 3),
        "achieved_flops_per_s": achieved_f,
        "achieved_bytes_per_s": achieved_b,
        "mfu": round(achieved_f / fl, 4),
        "bandwidth_util": round(achieved_b / bw, 4),
        # the roofline's floor for this program on this chip: the
        # measured/floor ratio says how much headroom is model-side
        "floor_step_s": round(t_total if time_est else 0.0, 9),
        "categories": cats,
    }
    if items_per_step:
        out["items_per_step"] = items_per_step
        out["items_per_sec"] = round(items_per_step / step_time_s, 1)
    return out
