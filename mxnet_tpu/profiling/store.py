"""In-process CostReport store: lazy capture specs, step times,
persistence.

The hot paths (``ndarray.invoke``, ``HybridBlock._run_cached``,
``Executor.forward``, ``TrainStep``) call ``register()`` with a jitted
callable + abstracted example args -- a dict insert, nothing else.  The
expensive part (``fn.lower().compile()`` -- which hits jax's executable
cache for anything already dispatched -- plus HLO parsing) runs at
``reports()`` / ``save()`` time, off the training path.

Step wall times recorded via ``record_step()`` attach per-label step
stats and a roofline section to the matching reports.
"""
from __future__ import annotations

import json
import os
import time

from .. import sync as _sync
from . import cost, roofline

COMBINED_SCHEMA = "mxprof.report.v1"
COMBINED_NAME = "report.json"

_lock = _sync.Lock(name="profiling.store")
_pending = {}      # key -> spec dict (label, fn, args, kind, meta)
_reports = {}      # key -> CostReport dict
_failed = set()    # keys whose lowering failed (don't retry forever)
_steps = {}        # label -> {"count","total_s","min_s","max_s","items"}
_live = {}         # key -> (label, fn, args); survives materialization so
#                    analysis.sharding's collective auditor can re-lower
#                    (cache-hit) any registered executable at audit time


def register(key, label, fn, args, kind="jit", **meta):
    """Queue one executable for lazy analysis (dedupes on ``key``)."""
    with _lock:
        if key in _pending or key in _reports or key in _failed:
            return
    import jax

    def _abstract(x):
        if hasattr(x, "shape") and hasattr(x, "dtype") and \
                not isinstance(x, jax.ShapeDtypeStruct):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x
    try:
        specs = jax.tree_util.tree_map(_abstract, args)
    except Exception:
        return
    with _lock:
        if key not in _pending and key not in _reports:
            _pending[key] = {"label": label, "fn": fn, "args": specs,
                             "kind": kind, "meta": meta}
            _live[key] = (label, fn, specs)


def executables():
    """Snapshot of every registered executable as ``(label, fn,
    abstract_args)`` tuples, in registration order.  Unlike
    ``_pending``, entries persist after report materialization -- the
    sharding sanitizer's collective-contract audit lowers them again
    (hitting jax's executable cache) whenever it runs."""
    with _lock:
        return list(_live.values())


def compiled_executables():
    """``(label, jax.stages.Compiled)`` for every registered
    executable, lowered at call time (hits jax's executable cache for
    anything already dispatched).  The shared audit surface: the
    sharding sanitizer's collective contract and the perf auditor
    (``analysis.perf.perf_audit``) both walk this instead of lowering
    independently.  Entries whose lowering fails (args gone stale) are
    skipped."""
    for label, fn, args in executables():
        try:
            yield label, fn.lower(*args).compile()
        except Exception:
            continue


def record_step(label, seconds, items=None):
    seconds = float(seconds)
    with _lock:
        st = _steps.setdefault(label, {"count": 0, "total_s": 0.0,
                                       "min_s": None, "max_s": None,
                                       "items": 0})
        st["count"] += 1
        st["total_s"] += seconds
        st["min_s"] = seconds if st["min_s"] is None \
            else min(st["min_s"], seconds)
        st["max_s"] = seconds if st["max_s"] is None \
            else max(st["max_s"], seconds)
        if items:
            st["items"] += int(items)
    from .. import telemetry as _telemetry
    if _telemetry._ENABLED:
        _telemetry.hooks.profiling_step(label, seconds)


def step_stats(label=None):
    with _lock:
        if label is not None:
            return dict(_steps.get(label, {}))
        return {k: dict(v) for k, v in _steps.items()}


def _materialize():
    """Analyze every pending spec (outside the lock: lowering can take
    a while and must not block the hot-path register)."""
    with _lock:
        todo = list(_pending.items())
        for k, _v in todo:
            del _pending[k]
    from .. import telemetry as _telemetry
    for key, spec in todo:
        t0 = time.perf_counter()
        rep = cost.analyze_jit(spec["fn"], spec["args"],
                               label=spec["label"], kind=spec["kind"],
                               **spec["meta"])
        dt = time.perf_counter() - t0
        if rep is None:
            with _lock:
                _failed.add(key)
            continue
        with _lock:
            _reports[key] = rep
        if _telemetry._ENABLED:
            _telemetry.hooks.profiling_capture(
                spec["label"], dt, flops=rep["totals"]["flops"])


def _annotate(rep):
    """Attach step stats + roofline when step times exist for the
    report's label."""
    st = _steps.get(rep["label"])
    if not st or not st["count"]:
        return rep
    mean = st["total_s"] / st["count"]
    rep = dict(rep)
    rep["step"] = {"count": st["count"], "mean_s": mean,
                   "min_s": st["min_s"], "max_s": st["max_s"],
                   "total_s": st["total_s"]}
    items = (st["items"] / st["count"]) if st.get("items") else None
    rep["roofline"] = roofline.build(rep, mean, items_per_step=items)
    return rep


def reports():
    """All CostReports, annotated, insertion-ordered."""
    _materialize()
    with _lock:
        reps = list(_reports.values())
        steps_snapshot = bool(_steps)
    return [(_annotate(r) if steps_snapshot else r) for r in reps]


def flops_per_step(label=None):
    """FLOPs of ONE dispatch of the labeled executable (``label=None``
    picks the first ``train_step``-kind report) from the materialized
    CostReports -- the goodput ledger's window-flops source
    (``obs.goodput.StepLedger(flops_per_step=...)``): window MFU =
    flops_per_step x steps / wall / device peak.  Materializes lazily
    (jax executable-cache hit for anything already dispatched); None
    when nothing matches."""
    for rep in reports():
        if (rep["label"] == label
                or (label is None and rep.get("kind") == "train_step")):
            return rep["totals"]["flops"]
    return None


def combined():
    """The combined artifact ``mxprof report`` / ``diff`` consume."""
    reps = reports()
    rollup = {}
    tot_f = tot_b = 0.0
    peak_hbm = 0
    for r in reps:
        tot_f += r["totals"]["flops"]
        tot_b += r["totals"]["bytes_accessed"]
        peak_hbm = max(peak_hbm, r["memory"]["peak_hbm_bytes"])
        for c, v in r["categories"].items():
            agg = rollup.setdefault(c, {"flops": 0, "bytes": 0,
                                        "instructions": 0})
            agg["flops"] += v["flops"]
            agg["bytes"] += v["bytes"]
            agg["instructions"] += v["instructions"]
    return {
        "schema": COMBINED_SCHEMA,
        "steps": step_stats(),
        "executables": reps,
        "totals": {"flops": tot_f, "bytes_accessed": tot_b,
                   "peak_hbm_bytes": peak_hbm},
        "categories": rollup,
    }


def _safe_name(label):
    return "".join(ch if ch.isalnum() or ch in "._-" else "_"
                   for ch in label) or "report"


def save(dirpath=None):
    """Write per-executable ``<label>.cost.json`` files and the
    combined ``report.json``; returns the combined path."""
    from . import report_dir
    dirpath = dirpath or report_dir() or "mxprof_reports"
    os.makedirs(dirpath, exist_ok=True)
    comb = combined()
    for rep in comb["executables"]:
        path = os.path.join(dirpath,
                            _safe_name(rep["label"]) + ".cost.json")
        with open(path, "w") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
    out = os.path.join(dirpath, COMBINED_NAME)
    with open(out, "w") as f:
        json.dump(comb, f, indent=1, sort_keys=True)
    return out


def clear():
    with _lock:
        _pending.clear()
        _reports.clear()
        _failed.clear()
        _steps.clear()
        _live.clear()
