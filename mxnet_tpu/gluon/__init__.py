"""``mx.gluon`` (reference: ``python/mxnet/gluon/``)."""
from . import loss, nn, parameter
from .block import Block, HybridBlock, SymbolBlock
from .parameter import Constant, Parameter, ParameterDict
from .trainer import Trainer
from . import data  # noqa: F401
from . import rnn  # noqa: F401
from . import model_zoo  # noqa: F401
from . import contrib  # noqa: F401
