"""Fused recurrent layers (reference: ``python/mxnet/gluon/rnn/rnn_layer.py``).

LSTM/GRU/RNN over the fused ``RNN`` op (``ops/nn.py :: _rnn`` -- lax.scan
over time).  Parameters follow the reference's per-layer naming
(``l0_i2h_weight`` ...); they are packed into the fused op's flat vector
inside the traced graph, so XLA sees one fused computation.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ..block import HybridBlock
from ..parameter import shape_is_known


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, mode, gates, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError("layout must be TNC or NTC, got %r" % layout)
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self._gates = gates
        with self.name_scope():
            for i in range(num_layers):
                for j in (["l", "r"] if bidirectional else ["l"]):
                    in_sz = input_size if i == 0 else hidden_size * self._dir
                    self._reg_params["%s%d_i2h_weight" % (j, i)] = \
                        self.params.get(
                            "%s%d_i2h_weight" % (j, i),
                            shape=(gates * hidden_size, in_sz),
                            init=i2h_weight_initializer,
                            allow_deferred_init=True)
                    self._reg_params["%s%d_h2h_weight" % (j, i)] = \
                        self.params.get(
                            "%s%d_h2h_weight" % (j, i),
                            shape=(gates * hidden_size, hidden_size),
                            init=h2h_weight_initializer)
                    self._reg_params["%s%d_i2h_bias" % (j, i)] = \
                        self.params.get(
                            "%s%d_i2h_bias" % (j, i),
                            shape=(gates * hidden_size,),
                            init=i2h_bias_initializer)
                    self._reg_params["%s%d_h2h_bias" % (j, i)] = \
                        self.params.get(
                            "%s%d_h2h_bias" % (j, i),
                            shape=(gates * hidden_size,),
                            init=h2h_bias_initializer)

    def infer_shape(self, x, *args):
        in_sz = x.shape[2] if self._layout == "TNC" else x.shape[2]
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                p = self._reg_params["%s%d_i2h_weight" % (j, i)]
                if not shape_is_known(p.shape):
                    layer_in = in_sz if i == 0 else \
                        self._hidden_size * self._dir
                    p.shape = (self._gates * self._hidden_size, layer_in)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        states = []
        for info in self.state_info(batch_size):
            states.append(F.zeros(info["shape"], **kwargs))
        return states

    def _pack_params(self, F, kwargs):
        chunks = []
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                for part in ("i2h_weight", "h2h_weight", "i2h_bias",
                             "h2h_bias"):
                    chunks.append(
                        F.Reshape(kwargs["%s%d_%s" % (j, i, part)],
                                  shape=(-1,)))
        return F.Concat(*chunks, dim=0) if len(chunks) > 1 else chunks[0]

    def hybrid_forward(self, F, inputs, states=None, **kwargs):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch, dtype=inputs.dtype)
        if not isinstance(states, (list, tuple)):
            states = [states]
        params = self._pack_params(F, kwargs)
        h0 = states[0]
        c0 = states[1] if self._mode == "lstm" else F.zeros_like(h0)
        out = F.RNN(inputs, params, h0, c0, state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout)
        if self._mode == "lstm":
            y, hy, cy = out
            new_states = [hy, cy]
        else:
            y, hy = out
            new_states = [hy]
        if self._layout == "NTC":
            y = F.swapaxes(y, dim1=0, dim2=1)
        if skip_states:
            return y
        return y, new_states

    def __repr__(self):
        return "%s(%s, hidden=%d, layers=%d%s)" % (
            type(self).__name__, self._input_size or "?", self._hidden_size,
            self._num_layers, ", bidirectional" if self._dir == 2 else "")


class RNN(_RNNLayer):
    """Vanilla multi-layer RNN (reference: ``rnn_layer.py :: RNN``)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, mode, 1, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]


class LSTM(_RNNLayer):
    """Fused LSTM (reference: ``rnn_layer.py :: LSTM``)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", 4, **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape}, {"shape": shape}]


class GRU(_RNNLayer):
    """Fused GRU (reference: ``rnn_layer.py :: GRU``)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", 3, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]
