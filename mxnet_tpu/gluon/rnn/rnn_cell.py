"""Unrolled RNN cells (reference: ``python/mxnet/gluon/rnn/rnn_cell.py``)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock


class RecurrentCell(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        return [F.zeros(info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll over time (reference: ``RecurrentCell.unroll``)."""
        from ... import ndarray as F
        axis = layout.find("T")
        batch = inputs.shape[1 - axis if axis <= 1 else 0]
        if begin_state is None:
            begin_state = self.begin_state(batch, dtype=inputs.dtype)
        states = begin_state
        outputs = []
        for t in range(length):
            step = F.squeeze(F.slice_axis(inputs, axis=axis, begin=t, end=t + 1),
                             axis=axis)
            out, states = self(step, states)
            outputs.append(out)
        if merge_outputs is None or merge_outputs:
            outputs = F.stack(*outputs, axis=axis)
        return outputs, states


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._act = activation
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size))
            self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                            init="zeros")
            self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                            init="zeros")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._act)
        return out, [out]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size))
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,), init="zeros")
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,), init="zeros")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        gates = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                                 num_hidden=4 * self._hidden_size) + \
            F.FullyConnected(states[0], h2h_weight, h2h_bias,
                             num_hidden=4 * self._hidden_size)
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.tanh(g)
        c = f * states[1] + i * g
        h = o * F.tanh(c)
        return h, [h, c]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size))
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,), init="zeros")
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,), init="zeros")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        ir, iz, inn = F.split(i2h, num_outputs=3, axis=-1)
        hr, hz, hn = F.split(h2h, num_outputs=3, axis=-1)
        r = F.sigmoid(ir + hr)
        z = F.sigmoid(iz + hz)
        n = F.tanh(inn + r * hn)
        h = (1 - z) * n + z * states[0]
        return h, [h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def add(self, cell):
        self._children[str(len(self._children))] = cell

    def state_info(self, batch_size=0):
        out = []
        for cell in self._children.values():
            out.extend(cell.state_info(batch_size))
        return out

    def __call__(self, inputs, states):
        return self.forward(inputs, states)

    def forward(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            out, new = cell(inputs, states[pos:pos + n])
            inputs = out
            pos += n
            next_states.extend(new)
        return inputs, next_states


class DropoutCell(RecurrentCell):
    def __init__(self, rate, **kwargs):
        super().__init__(**kwargs)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, inputs, states):
        return F.Dropout(inputs, p=self._rate), states


class ZoneoutCell(RecurrentCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell
        self._zo = zoneout_outputs
        self._zs = zoneout_states

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def forward(self, inputs, states):
        from ... import ndarray as F
        out, new_states = self.base_cell(inputs, states)
        if self._zo > 0:
            mask = F.Dropout(F.ones_like(out), p=self._zo)
            out = F.where(mask, out, out)
        return out, new_states
