"""``gluon.rnn`` (reference: ``python/mxnet/gluon/rnn/``)."""
from .rnn_cell import (DropoutCell, GRUCell, LSTMCell, RecurrentCell,
                       RNNCell, SequentialRNNCell, ZoneoutCell)
from .rnn_layer import GRU, LSTM, RNN
