"""Gluon Parameter / ParameterDict.

TPU-native re-design of ``python/mxnet/gluon/parameter.py :: Parameter,
ParameterDict``: deferred shape init, grad_req, lr_mult/wd_mult, cast for
AMP.  Single-array storage (the reference keeps one copy per GPU context;
here one jax.Array carries the device -- or a sharding, for the
data-parallel Trainer, where `jax.sharding` replaces per-context lists).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from .. import autograd, initializer
from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from ..ndarray import ndarray as _nd_mod


class DeferredInitializationError(MXNetError):
    """Parameter touched before its deferred shape was inferred
    (reference: ``parameter.py :: DeferredInitializationError``)."""


def shape_is_known(shape):
    if shape is None:
        return False
    return all(s is not None and s > 0 for s in shape)


class Parameter:
    """A weight/aux tensor of a Block (reference: ``Parameter``)."""

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = np.dtype(dtype) if dtype is not None else None
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self._allow_deferred_init = allow_deferred_init
        self._data = None          # NDArray once initialized
        self._deferred_init = None  # (init, ctx, default_init)
        self._trace_data = None    # NDArray wrapping a tracer during hybridize
        self._sharding = None      # jax NamedSharding for data-parallel runs

    # -- shape ---------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is not None and shape_is_known(self._shape):
            if tuple(new_shape) != self._shape:
                raise MXNetError(
                    "cannot reset shape of %s from %s to %s"
                    % (self.name, self._shape, new_shape))
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError("bad grad_req %r" % req)
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
            else:
                self._init_grad()

    # -- init ----------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Reference: ``Parameter.initialize`` -- allocates + fills data,
        or defers until the shape is known."""
        if self._data is not None and not force_reinit:
            return
        default_init = default_init or initializer.Uniform()
        ctx = ctx or current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]  # single jax.Array carries placement; list kept for API compat
        if not shape_is_known(self._shape):
            if not self._allow_deferred_init:
                raise MXNetError(
                    "cannot initialize %s: shape %s unknown and deferred "
                    "init not allowed" % (self.name, self._shape))
            self._deferred_init = (init, ctx, default_init)
            return
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        data = _nd_mod.zeros(self._shape, ctx=ctx, dtype=self.dtype)
        ini = init or self.init or default_init
        if not isinstance(ini, initializer.Initializer):
            ini = initializer.create(ini)
        ini(initializer.InitDesc(self.name), data)
        if self._sharding is not None:
            # deferred-init param of a mesh-replicated block: place the
            # fresh array with the recorded sharding (parallel.replicate_block).
            # put_replicated assembles the global array on a multi-host
            # mesh; cross-rank value sync happens at the next
            # _sync_initial_params (TrainStep._ensure_states)
            from ..parallel.mesh import put_replicated
            data._data = put_replicated(data._data, self._sharding)
        self._data = data
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if not shape_is_known(self._shape):
            raise DeferredInitializationError(
                "parameter %s has unknown shape %s" % (self.name, self._shape))
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    def _init_grad(self):
        self._data.attach_grad(self._grad_req)

    # -- access --------------------------------------------------------
    def _check_initialized(self):
        if self._trace_data is not None:
            return
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "parameter %s deferred; forward once or set shape"
                    % self.name)
            raise MXNetError(
                "parameter %s not initialized; call .initialize()" % self.name)

    def data(self, ctx=None):
        if self._trace_data is not None:
            return self._trace_data
        self._check_initialized()
        return self._data

    def list_data(self):
        return [self.data()]

    def list_ctx(self):
        self._check_initialized()
        return [self._data.context]

    @property
    def grad_or_none(self):
        return None if self._data is None else self._data._grad

    def grad(self, ctx=None):
        self._check_initialized()
        if self._data._grad is None:
            raise MXNetError(
                "parameter %s has grad_req='null'" % self.name)
        return self._data._grad

    def list_grad(self):
        return [self.grad()]

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            g = self._data._grad
            g._data = _nd_mod.zeros(g.shape, dtype=g.dtype)._data

    def set_data(self, data):
        """Rebind the parameter value.  During hybridize tracing, aux-state
        writes (e.g. BatchNorm running stats) are captured by the trace
        context instead (reference mutates aux vars through the engine)."""
        from .block import _active_trace
        tr = _active_trace()
        if tr is not None and isinstance(data, NDArray) and \
                _nd_mod._is_traced(data._data):
            tr.record_aux(self, data)
            self._trace_data = data
            return
        if self._data is None:
            if self._deferred_init is not None:
                self.shape = data.shape
                self._finish_deferred_init()
            else:
                raise MXNetError("parameter %s not initialized" % self.name)
        grad = self._data._grad
        req = self._data._grad_req
        new = data if isinstance(data, NDArray) else NDArray(data)
        if self.dtype is not None and new.dtype != self.dtype:
            # keep the declared dtype authoritative: a drifted rebind
            # would change traced-graph dtypes mid-model downstream
            new = new.astype(self.dtype)
        self._data = new
        self._data._grad = grad
        self._data._grad_req = req

    def cast(self, dtype):
        """AMP cast (reference: ``Parameter.cast``)."""
        self.dtype = np.dtype(dtype)
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data = self._data.astype(dtype)
            if had_grad:
                self._init_grad()

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data = self._data.as_in_context(
                ctx[0] if isinstance(ctx, (list, tuple)) else ctx)

    def var(self):
        from ..symbol import var
        return var(self.name, shape=self.shape, dtype=self.dtype)

    def _reduce(self):
        return self.data()

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self._shape, self.dtype)


class Constant(Parameter):
    """Non-trainable constant parameter (reference: ``Constant``)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = _nd_mod.array(value)
        self.value = value

        class _CInit(initializer.Initializer):
            def _init_weight(self, _, arr):
                arr[:] = value

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit())


class ParameterDict:
    """Prefix-scoped dictionary of Parameters (reference:
    ``ParameterDict``); ``get`` creates-or-shares."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        return "ParameterDict(%s)" % ", ".join(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        full = self._prefix + name
        if full in self._params:
            param = self._params[full]
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    if param.shape is None or not shape_is_known(param.shape):
                        param._shape = tuple(v) if not isinstance(v, int) else (v,)
                continue
            return param
        if self._shared is not None and full in self._shared._params:
            self._params[full] = self._shared._params[full]
            return self._params[full]
        param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def get_constant(self, name, value=None):
        full = self._prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError("duplicate parameter name %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        default = init or initializer.Uniform()
        for p in self.values():
            p.initialize(None, ctx, default, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def save(self, filename, strip_prefix=""):
        arg = {}
        for p in self.values():
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = p._reduce()
        _nd_mod.save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = _nd_mod.load(filename)
        if restore_prefix:
            loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in loaded:
                    raise MXNetError("parameter %s missing from file" % name)
        for name, data in loaded.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError("unknown parameter %s in file" % name)
                continue
            p = self._params[name]
            if p._data is None:
                p._shape = data.shape
                p.dtype = data.dtype
                p._deferred_init = None
                p._data = data.as_in_context(
                    (ctx[0] if isinstance(ctx, (list, tuple)) else ctx)
                    or current_context())
                if p._grad_req != "null":
                    p._init_grad()
            else:
                p.set_data(data.astype(p.dtype))
