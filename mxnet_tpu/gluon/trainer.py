"""Gluon Trainer (reference: ``python/mxnet/gluon/trainer.py``).

Applies an Optimizer to a set of Parameters after backward.  KVStore
integration: gradients reduce across devices through the KVStore API
(which on TPU is ICI collectives -- ``mxnet_tpu/kvstore.py``) before the
update, preserving the reference's ``update_on_kvstore`` semantics.
"""
from __future__ import annotations

import time

from .. import optimizer as opt
from .. import profiling as _profiling
from .. import telemetry as _telemetry
from ..base import MXNetError
from .parameter import Parameter, ParameterDict


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a dict/list of Parameters")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError("non-Parameter in Trainer params: %r" % (p,))
            self._params.append(p)
            self._param2idx[p.name] = i
        self._scale = 1.0
        optimizer_params = optimizer_params or {}
        if isinstance(optimizer, opt.Optimizer):
            self._optimizer = optimizer
        else:
            param_dict = {i: p for i, p in enumerate(self._params)}
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updater = opt.get_updater(self._optimizer)
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._kvstore_spec = kvstore
        self._compression_params = compression_params

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_kvstore(self):
        from .. import kvstore as kvs
        spec = self._kvstore_spec
        if spec is None:
            self._kvstore = None
        elif isinstance(spec, str):
            self._kvstore = kvs.create(spec) if spec else None
        else:
            self._kvstore = spec
        if self._kvstore is not None and self._compression_params:
            self._kvstore.set_gradient_compression(self._compression_params)
        self._dist_synced = set()
        self._sync_initial_params()
        self._kv_initialized = True

    def _sync_initial_params(self):
        """Reference semantics (kvstore_dist.h :: Init + Pull): rank
        0's initial weights are pushed to the servers and every worker
        pulls them back, so all ranks START identical even though each
        process's initializer drew from its own entropy.  Serverless
        analog: broadcast from rank 0.  Runs per step so params whose
        deferred init materializes LATER still get synced exactly once
        (the reference inits kvstore keys lazily per-param too).

        SPMD assumption (same as the reference's lazy kv.init, which is
        also a collective): deferred params must materialize at the
        SAME step on every rank -- host_broadcast is a world
        collective, so asymmetric materialization would desequence the
        collectives."""
        if self._kvstore is None or \
                not getattr(self._kvstore, "_is_dist", False):
            return
        from ..distributed import host_broadcast_bucketed, world
        if world()[0] <= 1:
            return
        todo = [p for p in self._params
                if p.name not in self._dist_synced and p._data is not None]
        if not todo:
            return
        # ONE flattened collective for the whole parameter set instead
        # of one RPC per tensor; results land back on each input's own
        # sharding (distributed._result_device), so mesh-sharded params
        # keep their layout
        synced = host_broadcast_bucketed([p._data._data for p in todo],
                                         root=0)
        for p, v in zip(todo, synced):
            p._data._data = v
            self._dist_synced.add(p.name)

    def _check_and_rescale_grad(self, scale):
        self._optimizer.rescale_grad = scale

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce (via kvstore/collectives) + optimizer update
        (reference: ``Trainer.step``)."""
        t0 = time.perf_counter() \
            if _telemetry._ENABLED or _profiling._ENABLED else None
        try:
            self._step_impl(batch_size, ignore_stale_grad)
        finally:
            if t0 is not None:
                dt = time.perf_counter() - t0
                if _telemetry._ENABLED:
                    _telemetry.hooks.trainer_step(dt, batch_size)
                if _profiling._ENABLED:
                    from ..profiling import timeline
                    timeline.record("trainer.step", t0, dt,
                                    {"batch": batch_size})

    def _step_impl(self, batch_size, ignore_stale_grad):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler is not None:
            # fp16 AMP: fold 1/loss_scale into the update's rescale
            # (unless amp.unscale already divided the grads), and skip the
            # whole update on overflow.  The check runs on POST-allreduce
            # gradients: the cross-device sum itself can overflow
            # (reference: amp.init_trainer + LossScaler semantics).
            if not getattr(self, "_amp_unscaled", False):
                self._optimizer.rescale_grad /= scaler.loss_scale
            self._amp_unscaled = False
            grads = [p._data._grad for p in self._params
                     if p.grad_req != "null" and p._data is not None
                     and p._data._grad is not None]
            overflow = scaler.has_overflow(grads)
            scaler.update_scale(overflow)
            if overflow:
                return
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        self._sync_initial_params()   # late deferred-init params
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null" and p._data is not None
                and p._data._grad is not None]
        if getattr(self._kvstore, "_is_dist", False):
            # legacy eager path (the hot path is the compiled SPMD
            # TrainStep, which never reaches here): ONE bucketed
            # collective for the whole gradient set, not one per tensor
            self._kvstore.pushpull_bucket(
                [i for i, _ in live], [p._data._grad for _, p in live],
                [p._data._grad for _, p in live])
            return
        for i, p in live:
            self._kvstore.pushpull(i, p._data._grad, out=p._data._grad)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updatable = []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if p._data._grad is None:
                if ignore_stale_grad:
                    continue
                raise MXNetError("parameter %s has no gradient; run "
                                 "backward first" % p.name)
            updatable.append((i, p))
        if self._try_fused_update(updatable):
            return
        for i, p in updatable:
            self._updater(i, p._data._grad, p._data)

    def _try_fused_update(self, updatable):
        """Group plain-SGD updates into ``multi_sgd(_mom)_update`` calls so
        an N-layer model costs O(N / aggregate_num) dispatches instead of
        O(N) (reference: ``optimizer_op.cc :: multi_sgd_update`` +
        ``MXNET_OPTIMIZER_AGGREGATION_SIZE``)."""
        import os
        from .. import ndarray as nd
        o = self._optimizer
        if type(o) is not opt.SGD or o.multi_precision or len(updatable) < 2:
            return False
        agg = int(os.environ.get("MXNET_OPTIMIZER_AGGREGATION_SIZE", 60))
        if agg < 2:
            return False
        upd = self._updater
        clip = o.clip_gradient if o.clip_gradient is not None else -1.0
        for s in range(0, len(updatable), agg):
            chunk = updatable[s:s + agg]
            lrs, wds = [], []
            for i, p in chunk:
                o._update_count(i)
                lrs.append(o._get_lr(i))
                wds.append(o._get_wd(i))
            n = len(chunk)
            if o.momentum != 0.0:
                for i, p in chunk:
                    if i not in upd.states:
                        upd.states[i] = \
                            o.create_state_multi_precision(i, p._data)
                data = []
                for i, p in chunk:
                    data += [p._data, p._data._grad, upd.states[i]]
                outs = nd.multi_sgd_mom_update(
                    *data, lrs=tuple(lrs), wds=tuple(wds),
                    momentum=o.momentum, rescale_grad=o.rescale_grad,
                    clip_gradient=clip, num_weights=n)
                for k, (i, p) in enumerate(chunk):
                    p._data._data = outs[k]._data
                    upd.states[i]._data = outs[n + k]._data
            else:
                data = []
                for i, p in chunk:
                    data += [p._data, p._data._grad]
                outs = nd.multi_sgd_update(
                    *data, lrs=tuple(lrs), wds=tuple(wds),
                    rescale_grad=o.rescale_grad, clip_gradient=clip,
                    num_weights=n)
                for k, (i, p) in enumerate(chunk):
                    p._data._data = outs[k]._data
        return True

    def get_states(self):
        """Optimizer state as an opaque bytes blob (what
        ``CheckpointManager`` stores for the ``trainer`` item)."""
        return self._updater.get_states(dump_optimizer=False)

    def set_states(self, states):
        self._updater.set_states(states)

    def save_states(self, fname):
        """Reference: ``Trainer.save_states`` -- optimizer state blob.
        Committed atomically (tmp+fsync+rename via mx.checkpoint): a
        SIGKILL mid-write can no longer leave a truncated .states file
        that loads garbage."""
        from ..checkpoint.core import atomic_write_bytes
        atomic_write_bytes(fname, self.get_states())

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self.set_states(f.read())
