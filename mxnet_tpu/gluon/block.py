"""Gluon Block / HybridBlock: the layer system and the hybridize engine.

TPU-native re-design of ``python/mxnet/gluon/block.py :: Block,
HybridBlock`` and the CachedOp executor
(``src/imperative/cached_op.cc :: CachedOp::Forward/Backward``).

The hybridize engine here IS the XLA path: ``hybridize()`` swaps the
imperative per-op dispatch for a shape-specialized ``jax.jit`` cache.

- Trace: the block's imperative forward runs once with tracer-wrapped
  NDArrays (parameters bound to traced values), capturing a pure function
  ``(params, inputs, rng_key) -> (outputs, aux_updates)``.  This replaces
  the reference's Symbol-proxy trace of ``hybrid_forward(F, ...)``.
- Aux state (BatchNorm running stats): mutations during trace are captured
  as extra functional outputs and rebound after each call -- the engine's
  mutable aux vars, done the XLA way.
- Randomness (Dropout): stateful-rng ops draw from a traced key stream; a
  fresh key is an explicit argument each call, keeping the compiled
  function pure.
- Backward: under ``autograd.record()`` the whole compiled graph becomes
  ONE tape node.  Forward runs as ``jit(vjp(pure_fn))`` returning a
  residual-carrying VJP pytree; backward is a second jitted call consuming
  it.  This mirrors CachedOp contributing its full graph to the tape
  (SURVEY.md §3.2) with both directions XLA-fused.
- Shape specialization: each (shapes, dtypes, train-flag) gets its own
  compiled entry -- the jit-cache answer to BucketingModule.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import numpy as np

import jax

from .. import autograd
from .. import ndarray as nd_mod
from .. import profiling as _profiling
from .. import random as _random_mod
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from ..ndarray.ndarray import _is_traced
from .parameter import (DeferredInitializationError, Parameter, ParameterDict,
                        shape_is_known)

_naming = threading.local()


def _naming_state():
    if not hasattr(_naming, "counters"):
        _naming.counters = [{}]
        _naming.prefixes = [""]
    return _naming


def _block_counters():
    return _naming_state().counters[-1]


_trace_tls = threading.local()


def _active_trace():
    return getattr(_trace_tls, "trace", None)


class _TraceContext:
    """Collects aux-state writes made while tracing a hybrid graph."""

    def __init__(self):
        self.aux_updates = OrderedDict()  # Parameter -> NDArray(tracer)

    def record_aux(self, param, data):
        self.aux_updates[param] = data

    def __enter__(self):
        self._prev = getattr(_trace_tls, "trace", None)
        _trace_tls.trace = self
        return self

    def __exit__(self, *a):
        _trace_tls.trace = self._prev


class Block:
    """Base container (reference: ``Block``)."""

    def __init__(self, prefix=None, params=None):
        self._empty_init()
        st = _naming_state()
        counters = st.counters[-1]
        if prefix is None:
            # Auto names are scoped: a block created inside a parent's
            # name_scope() gets the parent prefix prepended (reference
            # semantics -- keeps repeated submodules' params distinct).
            hint = type(self).__name__.lower()
            idx = counters.get(hint, 0)
            counters[hint] = idx + 1
            prefix = st.prefixes[-1] + "%s%d_" % (hint, idx)
        self._prefix = prefix
        self._scope_params = ParameterDict(prefix, shared=params)

    def _empty_init(self):
        # set via object.__setattr__ to dodge our __setattr__ hooks
        object.__setattr__(self, "_children", OrderedDict())
        object.__setattr__(self, "_reg_params", OrderedDict())
        object.__setattr__(self, "_forward_hooks", [])
        object.__setattr__(self, "_forward_pre_hooks", [])

    # -- attribute registration ---------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            self._children[name] = value
        elif isinstance(value, Parameter):
            self._reg_params[name] = value
        object.__setattr__(self, name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix.rstrip("_")

    @property
    def params(self):
        return self._scope_params

    def name_scope(self):
        import contextlib

        @contextlib.contextmanager
        def _scope():
            st = _naming_state()
            st.counters.append({})
            st.prefixes.append(self._prefix)
            try:
                yield self
            finally:
                st.counters.pop()
                st.prefixes.pop()
        return _scope()

    # -- parameter management -----------------------------------------
    def collect_params(self, select=None):
        """All parameters of self and descendants (reference:
        ``Block.collect_params``)."""
        out = ParameterDict(self._scope_params.prefix)
        pattern = re.compile(select) if select else None
        for p in self._all_params():
            if pattern is None or pattern.match(p.name):
                out._params[p.name] = p
        return out

    def _all_params(self, seen=None):
        seen = seen if seen is not None else set()
        for p in self._reg_params.values():
            if id(p) not in seen:
                seen.add(id(p))
                yield p
        for p in self._scope_params.values():
            if id(p) not in seen:
                seen.add(id(p))
                yield p
        for child in self._children.values():
            yield from child._all_params(seen)

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        """Cast parameters recursively (reference: ``Block.cast``).
        Subclasses may override with the same signature to adjust the
        dtype for their subtree (BatchNorm keeps statistics fp32)."""
        self._cast_impl(dtype, set())

    def _cast_impl(self, dtype, seen):
        for child in self._children.values():
            if type(child).cast is not Block.cast:
                # overriding subclass: honor its public hook (it
                # recurses its own subtree via super().cast)
                child.cast(dtype)
            else:
                child._cast_impl(dtype, seen)
        for p in list(self._reg_params.values()) + \
                list(self._scope_params.values()):
            if id(p) not in seen:
                seen.add(id(p))
                p.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -- structural save/load (reference: Block.save_parameters) ------
    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        arg = {k: p._reduce() for k, p in params.items() if p._data is not None
               or p._deferred_init is None}
        nd_mod.save(filename, arg)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        loaded = nd_mod.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        # accept both structural names and full prefixed names
        if loaded and not any(k in params for k in loaded):
            by_name = {p.name: p for p in params.values()}
            if any(k in by_name for k in loaded):
                params = by_name
        for name in loaded:
            if name not in params:
                if not ignore_extra:
                    raise MXNetError(
                        "parameter %r in file not found in Block; set "
                        "ignore_extra=True to skip" % name)
                continue
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise MXNetError(
                        "parameter %r missing from file; set "
                        "allow_missing=True to skip" % name)
        for name, data in loaded.items():
            if name not in params:
                continue
            p = params[name]
            if p._data is None:
                p._shape = data.shape
                p._deferred_init = None
                p._data = data.as_in_context(
                    (ctx[0] if isinstance(ctx, (list, tuple)) else ctx)
                    or current_context())
                if p.dtype is not None and np.dtype(p.dtype) != data.dtype \
                        and not cast_dtype:
                    p._data = p._data.astype(p.dtype)
                if p._grad_req != "null":
                    p._init_grad()
            else:
                p.set_data(data.astype(p.dtype))

    # -- hooks ---------------------------------------------------------
    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    # -- call ----------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        import sys
        npx = sys.modules.get("mxnet_tpu.numpy_extension")
        if npx is not None and npx.is_np_array():
            # npx.set_np(): blocks speak mx.np (reference semantics)
            from ..numpy import _view
            if isinstance(out, (list, tuple)):
                out = type(out)(_view(o) for o in out)
            else:
                out = _view(out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        """No-op on plain Blocks except recursing into children
        (reference behavior)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        lines = ["-" * 64,
                 "%-30s %-20s %s" % ("Layer", "Output", "Params"),
                 "=" * 64]
        total = 0

        def hook(block, inp, out):
            nonlocal total
            n = sum(int(np.prod(p.shape)) for p in block._reg_params.values()
                    if p.shape and shape_is_known(p.shape))
            total += n
            shape = out.shape if isinstance(out, NDArray) else "-"
            lines.append("%-30s %-20s %d" % (type(block).__name__, shape, n))

        handles = []
        for child in self._children.values():
            handles.append((child, hook))
            child._forward_hooks.append(hook)
        try:
            self(*inputs)
        finally:
            for child, h in handles:
                child._forward_hooks.remove(h)
        lines.append("=" * 64)
        lines.append("Total params (direct children): %d" % total)
        return "\n".join(lines)

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for name, child in self._children.items():
            lines.append("  (%s): %s" % (name, repr(child).replace("\n", "\n  ")))
        lines.append(")")
        return "\n".join(lines)


# Static fields of the compiled-entry cache key built in
# ``HybridBlock._call_cached``.  Op params are intentionally absent:
# they are baked into each trace as compile-time constants.  The
# retrace auditor (``mxnet_tpu.analysis.retrace``) cross-references
# this tuple against the op registry's param specs -- keep it in sync
# with the ``key = ...`` expression below.
_CACHE_KEY_STATIC = ("training", "amp_policy", "shape", "dtype")


def _cache_key_diff(new_key, old_keys):
    """Field-labeled diff of a fresh hybridize cache key against the
    closest existing entry -- the payload of the runtime retrace event
    (``telemetry.hooks.compile_event``).  Labels follow
    ``_CACHE_KEY_STATIC`` plus per-argument position, so a log line says
    e.g. ``changed=['arg0.shape']`` (bucketing) vs ``['training']``
    (train/eval duality) vs ``['amp_policy']``."""
    if not old_keys:
        return []
    # closest = most leading fields shared
    def score(k):
        n = 0
        for a, b in zip(k, new_key):
            if a == b:
                n += 1
        return n
    prev = max(old_keys, key=score)
    changed = []
    if prev[0] != new_key[0]:
        changed.append("training")
    if prev[1] != new_key[1]:
        changed.append("amp_policy")
    if len(prev) != len(new_key):
        changed.append("n_args")
    for i, (a, b) in enumerate(zip(prev[2:], new_key[2:])):
        if a[0] != b[0]:
            changed.append("arg%d.shape" % i)
        if a[1] != b[1]:
            changed.append("arg%d.dtype" % i)
    return changed


class _CacheEntry:
    """One compiled specialization of a hybridized block."""

    __slots__ = ("fwd_eval", "fwd_vjp", "bwd", "param_names", "diff_names",
                 "aux_params", "single_output", "_nondiff_names")

    def __init__(self):
        self.fwd_eval = None
        self.fwd_vjp = None
        self.bwd = None
        self.param_names = []
        self.diff_names = []
        self.aux_params = []
        self.single_output = True
        self._nondiff_names = []


class HybridBlock(Block):
    """Imperative/compiled dual-mode block (reference: ``HybridBlock``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        object.__setattr__(self, "_active", False)
        object.__setattr__(self, "_cached_entries", {})
        object.__setattr__(self, "_flags", {})

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        """Enable the compiled path (reference: ``HybridBlock.hybridize``;
        static_alloc/static_shape are implied by XLA and kept for API
        compatibility)."""
        object.__setattr__(self, "_active", active)
        object.__setattr__(self, "_cached_entries", {})
        self._flags.update({"static_alloc": static_alloc,
                            "static_shape": static_shape, **kwargs})
        for child in self._children.values():
            child.hybridize(active, static_alloc=static_alloc,
                            static_shape=static_shape, **kwargs)

    def infer_shape(self, *args):
        """Layer-specific deferred-shape rule; layers override
        (reference: ``HybridBlock.infer_shape`` via symbolic inference)."""
        raise MXNetError(
            "%s: cannot infer parameter shapes; either give explicit "
            "in_units/in_channels or override infer_shape"
            % type(self).__name__)

    # imperative composition used both eagerly and under trace
    def _forward_impl(self, *args):
        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._infer_and_finish(*args)
            params = {k: p.data() for k, p in self._reg_params.items()}
        return self.hybrid_forward(nd_mod, *args, **params)

    def _infer_and_finish(self, *args):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def forward(self, *args):
        from ..symbol.symbol import Symbol
        if any(isinstance(a, Symbol) for a in args):
            return self._symbolic_forward(*args)
        if self._active and _active_trace() is None and \
                all(isinstance(a, NDArray) for a in args):
            return self._call_cached(*args)
        return self._forward_impl(*args)

    def _symbolic_forward(self, *args):
        """Dual-F trace with F = mx.sym (reference: hybrid_forward's
        Symbol mode, used by export)."""
        from .. import symbol as sym_mod
        params = {k: p.var() for k, p in self._reg_params.items()}
        return self.hybrid_forward(sym_mod, *args, **params)

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError

    def export(self, path, epoch=0):
        """Serialize params for deployment (reference:
        ``HybridBlock.export`` writes ``-symbol.json`` + ``.params``;
        the graph side is provided by ``mxnet_tpu.symbol`` tracing)."""
        from ..symbol.export import export_block
        return export_block(self, path, epoch)

    def optimize_for(self, x, backend=None, **kwargs):
        self.hybridize()
        return self(x)

    def functionalize(self, training=True):
        """Return ``(pure_fn, param_names, params)`` where
        ``pure_fn(pvals: dict, ivals: list, rng_key) -> (outs, aux)`` is the
        block's forward as a pure jax function -- the building block for
        both the CachedOp cache and the multi-device pjit trainer
        (``mxnet_tpu.parallel``)."""
        params = [p for p in self._all_params() if p._data is not None]
        pmap = {p.name: p for p in params}
        block = self

        def pure_fn(pvals, ivals, rng_key):
            tr = _TraceContext()
            with tr, _random_mod.traced_stream(rng_key), \
                    autograd.pause(train_mode=training):
                for name, p in pmap.items():
                    p._trace_data = NDArray(pvals[name])
                try:
                    outs = block._forward_impl(*[NDArray(v) for v in ivals])
                finally:
                    aux = [(p, d) for p, d in tr.aux_updates.items()]
                    for p in pmap.values():
                        p._trace_data = None
            single = not isinstance(outs, (tuple, list))
            outs = [outs] if single else list(outs)
            aux_vals = {p.name: d._data for p, d in aux}
            return tuple(o._data for o in outs), aux_vals

        return pure_fn, [p.name for p in params], pmap

    # -- the CachedOp engine -------------------------------------------
    def _call_cached(self, *args):
        # first call may need deferred shape inference: run imperative once
        deferred = any(p._deferred_init is not None for p in self._all_params())
        if deferred:
            return self._forward_impl(*args)
        training = autograd.is_training()
        recording = autograd.is_recording()
        from .. import amp as _amp
        key = (training, _amp.policy_token()) + \
            tuple((a.shape, str(a.dtype)) for a in args)
        entry = self._cached_entries.get(key)
        if entry is None:
            if _telemetry._ENABLED:
                import time as _time
                old_keys = list(self._cached_entries)
                t0 = _time.perf_counter()
                entry = self._build_cache(args, training)
                _telemetry.hooks.compile_event(
                    "hybrid_cache",
                    seconds=_time.perf_counter() - t0,
                    retrace=bool(old_keys),
                    block=type(self).__name__,
                    cache_size=len(old_keys) + 1,
                    changed=_cache_key_diff(key, old_keys))
            else:
                entry = self._build_cache(args, training)
            self._cached_entries[key] = entry
        import contextlib
        from .. import profiler as _profiler
        scope = _profiler.scope("mx.cachedop:%s" % type(self).__name__) \
            if _profiler._scopes_enabled else contextlib.nullcontext()
        with scope:
            return self._run_cached(entry, args, recording)

    def _build_cache(self, args, training):
        """Trace the imperative forward into a pure jax function and jit it
        (reference: ``_build_cache`` -> ``CachedOp`` construction)."""
        entry = _CacheEntry()
        params = [p for p in self._all_params() if p._data is not None]
        entry.param_names = [p.name for p in params]
        pmap = {p.name: p for p in params}
        block = self

        def pure_fn(pvals, ivals, rng_key):
            tr = _TraceContext()
            with tr, _random_mod.traced_stream(rng_key), \
                    autograd.pause(train_mode=training):
                for name, p in pmap.items():
                    p._trace_data = NDArray(pvals[name])
                try:
                    outs = block._forward_impl(
                        *[NDArray(v) for v in ivals])
                finally:
                    aux = [(p, d) for p, d in tr.aux_updates.items()]
                    for p in pmap.values():
                        p._trace_data = None
            single = not isinstance(outs, (tuple, list))
            outs = [outs] if single else list(outs)
            aux_vals = {p.name: d._data for p, d in aux}
            return tuple(o._data for o in outs), aux_vals, single

        # probe trace via eval_shape to discover outputs/aux without compute
        pvals = {p.name: p._data._data for p in params}
        ivals = [a._data for a in args]
        probe_key = jax.random.PRNGKey(0)
        single_flag = [True]
        aux_names = [None]

        def fn2(pvals, ivals, rng_key):
            outs, aux, single = pure_fn(pvals, ivals, rng_key)
            single_flag[0] = single
            aux_names[0] = list(aux.keys())
            return outs, aux

        jax.eval_shape(fn2, pvals, ivals, probe_key)
        entry.single_output = single_flag[0]
        entry.aux_params = [pmap[n] for n in aux_names[0]]
        entry.diff_names = [p.name for p in params
                            if p._grad_req != "null" and
                            p.name not in aux_names[0]]
        diff_set = set(entry.diff_names)
        nondiff_names = [n for n in entry.param_names if n not in diff_set]

        def eval_fn(pvals, ivals, rng_key):
            outs, aux = fn2(pvals, ivals, rng_key)
            return outs, aux

        # no donation by design: pvals are the Parameter._data buffers
        # and the forward returns activations, not updated params -- the
        # inputs must survive the call (the donated whole-step program
        # is parallel.TrainStep, which rebinds its outputs)
        entry.fwd_eval = jax.jit(eval_fn)  # mxlint: disable=undonated-train-state

        def fwd_vjp(diff, nondiff, ivals, rng_key):
            def inner(d, i):
                merged = dict(nondiff)
                merged.update(d)
                return fn2(merged, i, rng_key)
            return jax.vjp(inner, diff, ivals)

        # same: diff/nondiff stay bound to Parameters across fwd+bwd (and
        # retain_graph backward may pull the residuals twice)
        entry.fwd_vjp = jax.jit(fwd_vjp)  # mxlint: disable=undonated-train-state
        entry.bwd = jax.jit(lambda vjp, cts: vjp(cts))
        entry._nondiff_names = nondiff_names
        return entry

    def _run_cached(self, entry, args, recording):
        import jax.numpy as jnp
        params = {n: p for n, p in
                  ((p.name, p) for p in self._all_params())
                  if n in set(entry.param_names)}
        pvals = {n: params[n]._data._data for n in entry.param_names}
        ivals = [a._data for a in args]
        rng_key = _random_mod.next_key()

        diff_vals = {n: pvals[n] for n in entry.diff_names}
        nondiff_vals = {n: pvals[n] for n in entry._nondiff_names}

        tracked_inputs = [a for a in args if a._is_tracked()]
        do_grad = recording and (entry.diff_names or tracked_inputs)
        if do_grad:
            (outs, aux), vjp = entry.fwd_vjp(diff_vals, nondiff_vals, ivals,
                                             rng_key)
        else:
            outs, aux = entry.fwd_eval(pvals, ivals, rng_key)
        if _profiling._ENABLED:
            # lazy cost capture (mx.profiling): keyed on the same
            # static fields as the hybridize cache, so each compiled
            # specialization yields exactly one CostReport
            ckey = ("hybrid", type(self).__name__, bool(do_grad)) + \
                tuple((a.shape, str(a.dtype)) for a in args)
            if do_grad:
                _profiling.capture_jit(
                    "hybrid:%s:train" % type(self).__name__,
                    entry.fwd_vjp,
                    (diff_vals, nondiff_vals, ivals, rng_key),
                    key=ckey, kind="hybrid_cache")
            else:
                _profiling.capture_jit(
                    "hybrid:%s" % type(self).__name__, entry.fwd_eval,
                    (pvals, ivals, rng_key), key=ckey,
                    kind="hybrid_cache")

        # rebind aux state (functional running stats -> parameter)
        for p in entry.aux_params:
            new = aux[p.name]
            grad = p._data._grad
            req = p._data._grad_req
            p._data = NDArray(new)
            p._data._grad = grad
            p._data._grad_req = req

        out_nds = [NDArray(o) for o in outs]

        if do_grad:
            diff_params = [params[n] for n in entry.diff_names]
            tape_inputs = [p._data for p in diff_params] + list(args)
            aux_zero_spec = {k: (v.shape, v.dtype) for k, v in aux.items()}
            n_outs = len(out_nds)
            bwd = entry.bwd
            diff_names = entry.diff_names

            def vjp_fn(cts):
                from ..ndarray import bulk as _bulk
                if not isinstance(cts, (tuple, list)):
                    cts = (cts,)
                # cotangents may be pending bulked-eager placeholders
                cts = tuple(_bulk.materialize(c) for c in cts)
                aux_cts = {k: jnp.zeros(s, d)
                           for k, (s, d) in aux_zero_spec.items()}
                d_diff, d_inputs = bwd(vjp, (tuple(cts), aux_cts))
                return tuple(d_diff[n] for n in diff_names) + tuple(d_inputs)

            node = autograd.TapeNode(tape_inputs, vjp_fn, n_outs,
                                     name=type(self).__name__ + "_cached")
            node._out_avals = [(o.shape, o.dtype) for o in out_nds]
            for i, o in enumerate(out_nds):
                o._ag_node = node
                o._ag_out_index = i
        return out_nds[0] if entry.single_output else out_nds


class SymbolBlock(HybridBlock):
    """Run a loaded symbolic graph as a block (reference: ``SymbolBlock``)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=None)
        self._outputs = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self._arg_params = params or {}
        for name, arr in self._arg_params.items():
            p = Parameter(name, shape=arr.shape, dtype=arr.dtype)
            p._data = arr if isinstance(arr, NDArray) else NDArray(arr)
            self._reg_params[name] = p
            self._scope_params._params[name] = p

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from ..symbol import load as sym_load
        sym = sym_load(symbol_file)
        params = nd_mod.load(param_file) if param_file else {}
        # strip the reference's "arg:"/"aux:" key prefixes
        params = {(k.split(":", 1)[1] if ":" in k else k): v
                  for k, v in params.items()}
        if isinstance(input_names, str):
            input_names = [input_names]
        return SymbolBlock(sym, input_names, params)

    def forward(self, *args):
        from ..symbol.symbol import _eval_symbol
        feed = dict(zip(self._inputs, args))
        for name, p in self._reg_params.items():
            feed[name] = p.data()
        outs = _eval_symbol(self._outputs, feed)
        return outs[0] if len(outs) == 1 else outs
