"""``gluon.model_zoo`` (reference: ``python/mxnet/gluon/model_zoo/``)."""
from . import vision
from .vision import get_model
from . import bert
from .bert import BERTModel, bert_base, bert_small, get_bert
