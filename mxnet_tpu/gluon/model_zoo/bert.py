"""BERT model family (BASELINE config 3).

TPU-native re-design of the BERT the reference serves through GluonNLP's
``model/bert.py`` on top of ``src/operator/contrib/transformer.cc``
kernels.  Pretraining heads (masked-LM + next-sentence) included; the
encoder runs the flash-attention path when no padding mask is given.
"""
from __future__ import annotations

from ..block import HybridBlock
from ..nn.basic_layers import Dense, Dropout, Embedding, LayerNorm
from ..nn.transformer import TransformerEncoder

__all__ = ["BERTModel", "bert_base", "bert_small", "get_bert"]


class BERTModel(HybridBlock):
    """BERT encoder with pretraining heads.

    Inputs: ``(token_ids, token_types)`` each (batch, seq); optional
    ``valid_mask`` (batch, seq_q, seq_k).  Outputs ``(mlm_scores,
    nsp_scores)`` -- (batch, seq, vocab) and (batch, 2).
    """

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 type_vocab_size=2, dropout=0.1, use_flash=None,
                 tp_mesh=None, tp_axis="tp", dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._tp_mesh = tp_mesh
        self._tp_axis = tp_axis
        tp_mode = tp_mesh is not None
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units, dtype=dtype)
            self.token_type_embed = Embedding(type_vocab_size, units,
                                              dtype=dtype)
            self.encoder = TransformerEncoder(
                units, hidden_size, num_layers, num_heads,
                max_length=max_length, dropout=dropout, use_flash=use_flash,
                tp_mode=tp_mode, dtype=dtype)
            # pooler over [CLS] for next-sentence prediction
            self.pooler = Dense(units, activation="tanh", flatten=False,
                                in_units=units, dtype=dtype)
            self.nsp_classifier = Dense(2, flatten=False, in_units=units,
                                        dtype=dtype)
            # masked-LM decoder (transform + vocab projection)
            self.mlm_transform = Dense(units, activation="gelu",
                                       flatten=False, in_units=units,
                                       dtype=dtype)
            self.mlm_ln = LayerNorm(in_channels=units)
            self.mlm_decoder = Dense(vocab_size, flatten=False,
                                     in_units=units, dtype=dtype)
            self.embed_drop = Dropout(dropout)

    def shard_tp(self, mesh=None, axis=None):
        """Megatron-shard the encoder over the ``tp`` mesh axis
        (attention q/k/v column-parallel, out row-parallel, FFN
        column+row): two psums per layer, inserted by XLA.  Embeddings,
        pooler, and heads stay replicated.  Call after ``initialize``
        (deferred params pick the sharding up at materialization)."""
        mesh = mesh if mesh is not None else self._tp_mesh
        axis = axis or self._tp_axis
        if mesh is None:
            raise ValueError("shard_tp needs a mesh (pass tp_mesh= at "
                             "construction or mesh= here)")
        from jax.sharding import PartitionSpec as P
        from ...parallel.tensor_parallel import place_param
        self.encoder.shard_tp(mesh, axis)
        for block in (self.word_embed, self.token_type_embed, self.pooler,
                      self.nsp_classifier, self.mlm_transform, self.mlm_ln,
                      self.mlm_decoder):
            for prm in block.collect_params().values():
                place_param(prm, mesh, P())
        return self

    def hybrid_forward(self, F, token_ids, token_types=None, valid_mask=None):
        x = self.word_embed(token_ids)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = self.embed_drop(x)
        seq_out = self.encoder(x, valid_mask)
        cls = F.slice_axis(seq_out, axis=1, begin=0, end=1) \
            .reshape((token_ids.shape[0], self._units))
        nsp = self.nsp_classifier(self.pooler(cls))
        mlm = self.mlm_decoder(self.mlm_ln(self.mlm_transform(seq_out)))
        return mlm, nsp


_SPECS = {
    # name: (units, hidden, layers, heads)
    "bert_base": (768, 3072, 12, 12),
    "bert_large": (1024, 4096, 24, 16),
    "bert_small": (256, 1024, 4, 4),
}


def get_bert(name, vocab_size=30522, max_length=512, dropout=0.1,
             use_flash=None, tp_mesh=None, **kwargs):
    """``tp_mesh``: a Mesh with a ``tp`` axis builds the encoder in
    tensor-parallel mode (separate column-parallel q/k/v); call
    ``net.shard_tp()`` after ``initialize`` to place the params."""
    units, hidden, layers, heads = _SPECS[name]
    return BERTModel(vocab_size=vocab_size, units=units, hidden_size=hidden,
                     num_layers=layers, num_heads=heads,
                     max_length=max_length, dropout=dropout,
                     use_flash=use_flash, tp_mesh=tp_mesh, **kwargs)


def bert_base(**kwargs):
    """BERT-base: 12 layers, 768 units, 12 heads (BASELINE config 3)."""
    return get_bert("bert_base", **kwargs)


def bert_small(**kwargs):
    """Small BERT for tests/CI."""
    return get_bert("bert_small", **kwargs)
