"""BERT model family (BASELINE config 3).

TPU-native re-design of the BERT the reference serves through GluonNLP's
``model/bert.py`` on top of ``src/operator/contrib/transformer.cc``
kernels.  Pretraining heads (masked-LM + next-sentence) included; the
encoder runs the flash-attention path when no padding mask is given.
"""
from __future__ import annotations

from ..block import HybridBlock
from ..nn.basic_layers import Dense, Dropout, Embedding, LayerNorm
from ..nn.transformer import TransformerEncoder

__all__ = ["BERTModel", "bert_base", "bert_small", "get_bert"]


class BERTModel(HybridBlock):
    """BERT encoder with pretraining heads.

    Inputs: ``(token_ids, token_types)`` each (batch, seq); optional
    ``valid_mask`` (batch, seq_q, seq_k).  Outputs ``(mlm_scores,
    nsp_scores)`` -- (batch, seq, vocab) and (batch, 2).
    """

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 type_vocab_size=2, dropout=0.1, use_flash=False,
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units, dtype=dtype)
            self.token_type_embed = Embedding(type_vocab_size, units,
                                              dtype=dtype)
            self.encoder = TransformerEncoder(
                units, hidden_size, num_layers, num_heads,
                max_length=max_length, dropout=dropout, use_flash=use_flash,
                dtype=dtype)
            # pooler over [CLS] for next-sentence prediction
            self.pooler = Dense(units, activation="tanh", flatten=False,
                                in_units=units, dtype=dtype)
            self.nsp_classifier = Dense(2, flatten=False, in_units=units,
                                        dtype=dtype)
            # masked-LM decoder (transform + vocab projection)
            self.mlm_transform = Dense(units, activation="gelu",
                                       flatten=False, in_units=units,
                                       dtype=dtype)
            self.mlm_ln = LayerNorm(in_channels=units)
            self.mlm_decoder = Dense(vocab_size, flatten=False,
                                     in_units=units, dtype=dtype)
            self.embed_drop = Dropout(dropout)

    def hybrid_forward(self, F, token_ids, token_types=None, valid_mask=None):
        x = self.word_embed(token_ids)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = self.embed_drop(x)
        seq_out = self.encoder(x, valid_mask)
        cls = F.slice_axis(seq_out, axis=1, begin=0, end=1) \
            .reshape((token_ids.shape[0], self._units))
        nsp = self.nsp_classifier(self.pooler(cls))
        mlm = self.mlm_decoder(self.mlm_ln(self.mlm_transform(seq_out)))
        return mlm, nsp


_SPECS = {
    # name: (units, hidden, layers, heads)
    "bert_base": (768, 3072, 12, 12),
    "bert_large": (1024, 4096, 24, 16),
    "bert_small": (256, 1024, 4, 4),
}


def get_bert(name, vocab_size=30522, max_length=512, dropout=0.1,
             use_flash=False, **kwargs):
    units, hidden, layers, heads = _SPECS[name]
    return BERTModel(vocab_size=vocab_size, units=units, hidden_size=hidden,
                     num_layers=layers, num_heads=heads,
                     max_length=max_length, dropout=dropout,
                     use_flash=use_flash, **kwargs)


def bert_base(**kwargs):
    """BERT-base: 12 layers, 768 units, 12 heads (BASELINE config 3)."""
    return get_bert("bert_base", **kwargs)


def bert_small(**kwargs):
    """Small BERT for tests/CI."""
    return get_bert("bert_small", **kwargs)
