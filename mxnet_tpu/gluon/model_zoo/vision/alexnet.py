"""AlexNet (reference: ``gluon/model_zoo/vision/alexnet.py``).

``layout`` threads end to end (NCHW default, NHWC for the TPU-friendly
channels-last path) -- the perflint ``layout-hostile-conv`` contract
for every model_zoo net.
"""
from ... import nn
from ...block import HybridBlock


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(64, 11, 4, 2, activation="relu",
                                        layout=layout))
            self.features.add(nn.MaxPool2D(3, 2, layout=layout))
            self.features.add(nn.Conv2D(192, 5, padding=2,
                                        activation="relu", layout=layout))
            self.features.add(nn.MaxPool2D(3, 2, layout=layout))
            self.features.add(nn.Conv2D(384, 3, padding=1,
                                        activation="relu", layout=layout))
            self.features.add(nn.Conv2D(256, 3, padding=1,
                                        activation="relu", layout=layout))
            self.features.add(nn.Conv2D(256, 3, padding=1,
                                        activation="relu", layout=layout))
            self.features.add(nn.MaxPool2D(3, 2, layout=layout))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def alexnet(**kwargs):
    kwargs.pop("pretrained", None)
    return AlexNet(**kwargs)
