"""Inception V3 (reference:
``python/mxnet/gluon/model_zoo/vision/inception.py`` -- architecture per
Szegedy et al., "Rethinking the Inception Architecture").

Built against this framework's HybridBlock API; every mixed block is a
HybridConcurrent-style parallel of conv towers concatenated on channels
-- shapes are static, so XLA fuses each tower and the concat into one
region.  Input convention: (N, 3, 299, 299) under the default NCHW
``layout``; the channel concat follows the layout's channel axis.
"""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock


def _conv(channels, kernel_size, strides=1, padding=0, layout="NCHW"):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, kernel_size=kernel_size, strides=strides,
                      padding=padding, use_bias=False, layout=layout),
            nn.BatchNorm(epsilon=0.001, axis=layout.index("C")),
            nn.Activation("relu"))
    return out


class _Tower(HybridBlock):
    """One branch: a sequence of conv units."""

    def __init__(self, specs, pool_first=None, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = nn.HybridSequential()
            if pool_first == "avg":
                self.body.add(nn.AvgPool2D(pool_size=3, strides=1,
                                           padding=1, layout=layout))
            elif pool_first == "max":
                self.body.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           layout=layout))
            for (c, k, s, p) in specs:
                self.body.add(_conv(c, k, s, p, layout=layout))

    def hybrid_forward(self, F, x):
        return self.body(x)


class _Mixed(HybridBlock):
    """Channel-concat of parallel towers."""

    def __init__(self, towers, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self._c_axis = layout.index("C")
        with self.name_scope():
            self.towers = nn.HybridSequential()
            for t in towers:
                self.towers.add(t)

    def hybrid_forward(self, F, x):
        return F.Concat(*[t(x) for t in self.towers], dim=self._c_axis)


def _mixed_a(pool_features, layout="NCHW"):
    return _Mixed([
        _Tower([(64, 1, 1, 0)], layout=layout),
        _Tower([(48, 1, 1, 0), (64, 5, 1, 2)], layout=layout),
        _Tower([(64, 1, 1, 0), (96, 3, 1, 1), (96, 3, 1, 1)],
               layout=layout),
        _Tower([(pool_features, 1, 1, 0)], pool_first="avg",
               layout=layout),
    ], layout=layout)


def _mixed_b(layout="NCHW"):
    return _Mixed([
        _Tower([(384, 3, 2, 0)], layout=layout),
        _Tower([(64, 1, 1, 0), (96, 3, 1, 1), (96, 3, 2, 0)],
               layout=layout),
        _Tower([], pool_first="max", layout=layout),
    ], layout=layout)


def _mixed_c(channels_7x7, layout="NCHW"):
    c = channels_7x7
    return _Mixed([
        _Tower([(192, 1, 1, 0)], layout=layout),
        _Tower([(c, 1, 1, 0), (c, (1, 7), 1, (0, 3)),
                (192, (7, 1), 1, (3, 0))], layout=layout),
        _Tower([(c, 1, 1, 0), (c, (7, 1), 1, (3, 0)),
                (c, (1, 7), 1, (0, 3)), (c, (7, 1), 1, (3, 0)),
                (192, (1, 7), 1, (0, 3))], layout=layout),
        _Tower([(192, 1, 1, 0)], pool_first="avg", layout=layout),
    ], layout=layout)


def _mixed_d(layout="NCHW"):
    return _Mixed([
        _Tower([(192, 1, 1, 0), (320, 3, 2, 0)], layout=layout),
        _Tower([(192, 1, 1, 0), (192, (1, 7), 1, (0, 3)),
                (192, (7, 1), 1, (3, 0)), (192, 3, 2, 0)],
               layout=layout),
        _Tower([], pool_first="max", layout=layout),
    ], layout=layout)


class _MixedE(HybridBlock):
    """The expanded-output block: two towers themselves fork 1x3/3x1."""

    def __init__(self, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self._c_axis = layout.index("C")
        with self.name_scope():
            self.b1 = _conv(320, 1, layout=layout)
            self.b2_stem = _conv(384, 1, layout=layout)
            self.b2_a = _conv(384, (1, 3), 1, (0, 1), layout=layout)
            self.b2_b = _conv(384, (3, 1), 1, (1, 0), layout=layout)
            self.b3_stem = nn.HybridSequential()
            self.b3_stem.add(_conv(448, 1, layout=layout),
                             _conv(384, 3, 1, 1, layout=layout))
            self.b3_a = _conv(384, (1, 3), 1, (0, 1), layout=layout)
            self.b3_b = _conv(384, (3, 1), 1, (1, 0), layout=layout)
            self.b4 = nn.HybridSequential()
            self.b4.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1,
                                     layout=layout),
                        _conv(192, 1, layout=layout))

    def hybrid_forward(self, F, x):
        y2 = self.b2_stem(x)
        y3 = self.b3_stem(x)
        return F.Concat(self.b1(x), self.b2_a(y2), self.b2_b(y2),
                        self.b3_a(y3), self.b3_b(y3), self.b4(x),
                        dim=self._c_axis)


class Inception3(HybridBlock):
    """Reference: ``Inception3`` (inception v3, 299x299 input)."""

    def __init__(self, classes=1000, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential()
            self.features.add(
                _conv(32, 3, 2, 0, layout=layout),
                _conv(32, 3, 1, 0, layout=layout),
                _conv(64, 3, 1, 1, layout=layout),
                nn.MaxPool2D(pool_size=3, strides=2, layout=layout),
                _conv(80, 1, 1, 0, layout=layout),
                _conv(192, 3, 1, 0, layout=layout),
                nn.MaxPool2D(pool_size=3, strides=2, layout=layout),
                _mixed_a(32, layout), _mixed_a(64, layout),
                _mixed_a(64, layout),
                _mixed_b(layout),
                _mixed_c(128, layout), _mixed_c(160, layout),
                _mixed_c(160, layout), _mixed_c(192, layout),
                _mixed_d(layout),
                _MixedE(layout=layout), _MixedE(layout=layout),
                nn.GlobalAvgPool2D(layout=layout),
                nn.Dropout(0.5),
            )
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def inception_v3(pretrained=False, classes=1000, **kwargs):
    """Reference: ``vision.inception_v3``.  ``pretrained`` weights are
    not shipped (no network in this environment); pass a .params path to
    ``net.load_parameters`` instead."""
    if pretrained:
        from ....base import MXNetError
        raise MXNetError("pretrained weights are not bundled; use "
                         "net.load_parameters(path)")
    return Inception3(classes=classes, **kwargs)
