"""VGG (reference: ``gluon/model_zoo/vision/vgg.py``).

``layout`` threads end to end (NCHW default, NHWC channels-last) --
the perflint ``layout-hostile-conv`` contract.
"""
from ....base import MXNetError
from ... import nn
from ...block import HybridBlock

vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        c_axis = layout.index("C")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for i, num in enumerate(layers):
                for _ in range(num):
                    self.features.add(nn.Conv2D(filters[i], 3, padding=1,
                                                layout=layout))
                    if batch_norm:
                        self.features.add(nn.BatchNorm(axis=c_axis))
                    self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(2, 2, layout=layout))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_vgg(num_layers, **kwargs):
    kwargs.pop("pretrained", None)
    if num_layers not in vgg_spec:
        raise MXNetError("bad vgg depth %d" % num_layers)
    layers, filters = vgg_spec[num_layers]
    return VGG(layers, filters, **kwargs)


def vgg11(**kw):
    return get_vgg(11, **kw)


def vgg13(**kw):
    return get_vgg(13, **kw)


def vgg16(**kw):
    return get_vgg(16, **kw)


def vgg19(**kw):
    return get_vgg(19, **kw)


def vgg11_bn(**kw):
    return get_vgg(11, batch_norm=True, **kw)


def vgg13_bn(**kw):
    return get_vgg(13, batch_norm=True, **kw)


def vgg16_bn(**kw):
    return get_vgg(16, batch_norm=True, **kw)


def vgg19_bn(**kw):
    return get_vgg(19, batch_norm=True, **kw)
