"""SqueezeNet (reference: ``gluon/model_zoo/vision/squeezenet.py``).

``layout`` threads end to end (NCHW default, NHWC channels-last); the
fire-module expand concat follows the layout's channel axis.
"""
from ....base import MXNetError
from ... import nn
from ...block import HybridBlock


def _make_fire(squeeze_channels, expand1x1_channels, expand3x3_channels,
               layout="NCHW"):
    out = nn.HybridSequential(prefix="")
    out.add(_make_fire_conv(squeeze_channels, 1, layout=layout))

    paths = _FirePaths(expand1x1_channels, expand3x3_channels,
                       layout=layout)
    out.add(paths)
    return out


def _make_fire_conv(channels, kernel_size, padding=0, layout="NCHW"):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels, kernel_size, padding=padding,
                      layout=layout))
    out.add(nn.Activation("relu"))
    return out


class _FirePaths(HybridBlock):
    def __init__(self, c1, c3, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        self._c_axis = layout.index("C")
        self.p1 = _make_fire_conv(c1, 1, layout=layout)
        self.p3 = _make_fire_conv(c3, 3, 1, layout=layout)

    def hybrid_forward(self, F, x):
        return F.Concat(self.p1(x), self.p3(x), dim=self._c_axis)


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, layout="NCHW", **kwargs):
        super().__init__(**kwargs)
        if version not in ("1.0", "1.1"):
            raise MXNetError("version must be 1.0 or 1.1")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if version == "1.0":
                self.features.add(nn.Conv2D(96, 7, 2, layout=layout))
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                self.features.add(_make_fire(16, 64, 64, layout))
                self.features.add(_make_fire(16, 64, 64, layout))
                self.features.add(_make_fire(32, 128, 128, layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                self.features.add(_make_fire(32, 128, 128, layout))
                self.features.add(_make_fire(48, 192, 192, layout))
                self.features.add(_make_fire(48, 192, 192, layout))
                self.features.add(_make_fire(64, 256, 256, layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                self.features.add(_make_fire(64, 256, 256, layout))
            else:
                self.features.add(nn.Conv2D(64, 3, 2, layout=layout))
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                self.features.add(_make_fire(16, 64, 64, layout))
                self.features.add(_make_fire(16, 64, 64, layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                self.features.add(_make_fire(32, 128, 128, layout))
                self.features.add(_make_fire(32, 128, 128, layout))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True,
                                               layout=layout))
                self.features.add(_make_fire(48, 192, 192, layout))
                self.features.add(_make_fire(48, 192, 192, layout))
                self.features.add(_make_fire(64, 256, 256, layout))
                self.features.add(_make_fire(64, 256, 256, layout))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, 1, layout=layout))
            self.output.add(nn.Activation("relu"))
            self.output.add(nn.GlobalAvgPool2D(layout=layout))
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def squeezenet1_0(**kw):
    kw.pop("pretrained", None)
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    kw.pop("pretrained", None)
    return SqueezeNet("1.1", **kw)
