"""``gluon.model_zoo.vision`` (reference:
``python/mxnet/gluon/model_zoo/vision/__init__.py :: get_model``)."""
from ....base import MXNetError
from .resnet import *  # noqa: F401,F403
from .resnet import get_resnet
from .alexnet import alexnet
from .vgg import (vgg11, vgg13, vgg16, vgg19, vgg11_bn, vgg13_bn, vgg16_bn,
                  vgg19_bn, get_vgg)
from .mobilenet import (mobilenet1_0, mobilenet0_75, mobilenet0_5,
                        mobilenet0_25, mobilenet_v2_1_0, mobilenet_v2_0_75,
                        mobilenet_v2_0_5, mobilenet_v2_0_25, get_mobilenet,
                        get_mobilenet_v2)
from .squeezenet import squeezenet1_0, squeezenet1_1
from .densenet import densenet121, densenet161, densenet169, densenet201
from .inception import inception_v3


def get_model(name, **kwargs):
    models = {
        "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
        "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
        "resnet152_v1": resnet152_v1,
        "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
        "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
        "resnet152_v2": resnet152_v2,
        "alexnet": alexnet,
        "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
        "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
        "vgg19_bn": vgg19_bn,
        "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
        "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
        "mobilenetv2_1.0": mobilenet_v2_1_0,
        "mobilenetv2_0.75": mobilenet_v2_0_75,
        "mobilenetv2_0.5": mobilenet_v2_0_5,
        "mobilenetv2_0.25": mobilenet_v2_0_25,
        "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
        "densenet121": densenet121, "densenet161": densenet161,
        "densenet169": densenet169, "densenet201": densenet201,
        "inceptionv3": inception_v3,
    }
    name = name.lower()
    if name not in models:
        raise MXNetError("model %r not in zoo; available: %s"
                         % (name, sorted(models)))
    return models[name](**kwargs)
