"""DenseNet (reference: ``gluon/model_zoo/vision/densenet.py``).

``layout`` threads end to end (NCHW default, NHWC channels-last);
the dense-block concat follows the layout's channel axis.
"""
from ... import nn
from ...block import HybridBlock


class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, layout="NCHW",
                 **kwargs):
        super().__init__(**kwargs)
        self._c_axis = layout.index("C")
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.BatchNorm(axis=self._c_axis))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(bn_size * growth_rate, 1, use_bias=False,
                                layout=layout))
        self.body.add(nn.BatchNorm(axis=self._c_axis))
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(growth_rate, 3, padding=1, use_bias=False,
                                layout=layout))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def hybrid_forward(self, F, x):
        out = self.body(x)
        return F.Concat(x, out, dim=self._c_axis)


def _make_dense_block(num_layers, bn_size, growth_rate, dropout,
                      stage_index, layout="NCHW"):
    out = nn.HybridSequential(prefix="stage%d_" % stage_index)
    for _ in range(num_layers):
        out.add(_DenseLayer(growth_rate, bn_size, dropout, layout=layout))
    return out


def _make_transition(num_output_features, layout="NCHW"):
    out = nn.HybridSequential(prefix="")
    out.add(nn.BatchNorm(axis=layout.index("C")))
    out.add(nn.Activation("relu"))
    out.add(nn.Conv2D(num_output_features, 1, use_bias=False,
                      layout=layout))
    out.add(nn.AvgPool2D(2, 2, layout=layout))
    return out


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, layout="NCHW",
                 **kwargs):
        super().__init__(**kwargs)
        c_axis = layout.index("C")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(num_init_features, 7, 2, 3,
                                        use_bias=False, layout=layout))
            self.features.add(nn.BatchNorm(axis=c_axis))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1, layout=layout))
            num_features = num_init_features
            for i, num_layers in enumerate(block_config):
                self.features.add(_make_dense_block(
                    num_layers, bn_size, growth_rate, dropout, i + 1,
                    layout=layout))
                num_features += num_layers * growth_rate
                if i != len(block_config) - 1:
                    self.features.add(_make_transition(num_features // 2,
                                                       layout=layout))
                    num_features //= 2
            self.features.add(nn.BatchNorm(axis=c_axis))
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D(layout=layout))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


densenet_spec = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
}


def _get(num, **kw):
    kw.pop("pretrained", None)
    f, g, b = densenet_spec[num]
    return DenseNet(f, g, b, **kw)


def densenet121(**kw):
    return _get(121, **kw)


def densenet161(**kw):
    return _get(161, **kw)


def densenet169(**kw):
    return _get(169, **kw)


def densenet201(**kw):
    return _get(201, **kw)
