"""Convolution and pooling layers (reference:
``python/mxnet/gluon/nn/conv_layers.py``)."""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock


def _tuplify(v, n):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, **kwargs):
        super().__init__(**kwargs)
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._op_name = op_name
        self._act = activation
        self._groups = groups
        self._kernel = kernel_size
        self._layout = layout
        # channel axis in the data layout; weight layout is derived from it
        # (ops/nn.py::_conv_dnums): NCHW -> OIHW, NHWC -> OHWI
        self._c_axis = layout.index("C")
        channels_last = self._c_axis == ndim + 1
        with self.name_scope():
            if op_name == "Convolution":
                ic = in_channels // groups if in_channels else 0
                if channels_last:
                    wshape = (channels,) + tuple(kernel_size) + (ic,)
                else:
                    wshape = (channels, ic) + tuple(kernel_size)
            else:  # Deconvolution: (in, out/groups, *k)
                if channels_last:
                    wshape = (in_channels if in_channels else 0,) \
                        + tuple(kernel_size) + (channels // groups,)
                else:
                    wshape = (in_channels if in_channels else 0,
                              channels // groups) + tuple(kernel_size)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x):
        c = x.shape[self._c_axis]
        channels_last = self._c_axis == len(self._kernel) + 1
        if self._op_name == "Convolution":
            if channels_last:
                self.weight.shape = (self._channels,) + tuple(self._kernel) \
                    + (c // self._groups,)
            else:
                self.weight.shape = (self._channels, c // self._groups) \
                    + tuple(self._kernel)
        else:
            if channels_last:
                self.weight.shape = (c,) + tuple(self._kernel) \
                    + (self._channels // self._groups,)
            else:
                self.weight.shape = (c, self._channels // self._groups) \
                    + tuple(self._kernel)

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        out = op(x, weight, bias, no_bias=bias is None, **self._kwargs)
        if self._act:
            out = F.Activation(out, act_type=self._act)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 1), _tuplify(strides, 1),
                         _tuplify(padding, 1), _tuplify(dilation, 1), groups,
                         layout, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 2), _tuplify(strides, 2),
                         _tuplify(padding, 2), _tuplify(dilation, 2), groups,
                         layout, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 3), _tuplify(strides, 3),
                         _tuplify(padding, 3), _tuplify(dilation, 3), groups,
                         layout, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 2), _tuplify(strides, 2),
                         _tuplify(padding, 2), _tuplify(dilation, 2), groups,
                         layout, op_name="Deconvolution",
                         adj=_tuplify(output_padding, 2), **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW", **kwargs):
        super().__init__(channels, _tuplify(kernel_size, 1), _tuplify(strides, 1),
                         _tuplify(padding, 1), _tuplify(dilation, 1), groups,
                         layout, op_name="Deconvolution",
                         adj=_tuplify(output_padding, 1), **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, global_pool, pool_type,
                 layout, count_include_pad=None, ceil_mode=False, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
            "layout": layout}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplify(pool_size, 1),
                         _tuplify(strides, 1) if strides is not None else None,
                         _tuplify(padding, 1), False, "max", layout,
                         ceil_mode=ceil_mode, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplify(pool_size, 2),
                         _tuplify(strides, 2) if strides is not None else None,
                         _tuplify(padding, 2), False, "max", layout,
                         ceil_mode=ceil_mode, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tuplify(pool_size, 3),
                         _tuplify(strides, 3) if strides is not None else None,
                         _tuplify(padding, 3), False, "max", layout,
                         ceil_mode=ceil_mode, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuplify(pool_size, 1),
                         _tuplify(strides, 1) if strides is not None else None,
                         _tuplify(padding, 1), False, "avg", layout,
                         count_include_pad, ceil_mode, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuplify(pool_size, 2),
                         _tuplify(strides, 2) if strides is not None else None,
                         _tuplify(padding, 2), False, "avg", layout,
                         count_include_pad, ceil_mode, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuplify(pool_size, 3),
                         _tuplify(strides, 3) if strides is not None else None,
                         _tuplify(padding, 3), False, "avg", layout,
                         count_include_pad, ceil_mode, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), True, "max", layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), True, "max", layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, "max", layout,
                         **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), True, "avg", layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), True, "avg", layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, "avg", layout,
                         **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        p = _tuplify(padding, 2)
        self._pad_width = (0, 0, 0, 0, p[0], p[0], p[1], p[1])

    def hybrid_forward(self, F, x):
        return F.Pad(x, mode="reflect", pad_width=self._pad_width)
