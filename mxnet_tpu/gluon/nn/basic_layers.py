"""Basic Gluon layers (reference: ``python/mxnet/gluon/nn/basic_layers.py``)."""
from __future__ import annotations

import numpy as np

from ... import autograd
from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import shape_is_known


class Sequential(Block):
    """Imperative stack (reference: ``Sequential``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self._children[str(len(self._children))] = b

    def forward(self, x):
        for b in self._children.values():
            x = b(x)
        return x

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def hybridize(self, active=True, **kwargs):
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Compilable stack (reference: ``HybridSequential``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self._children[str(len(self._children))] = b

    def _forward_impl(self, x):
        for b in self._children.values():
            x = b(x)
        return x

    def hybrid_forward(self, F, x):
        for b in self._children.values():
            x = b(x)
        return x

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Fully-connected layer (reference: ``Dense``); weight (units,
    in_units), deferred in_units."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self._act:
            out = F.Activation(out, act_type=self._act)
        return out

    def __repr__(self):
        return "Dense(%s -> %s)" % (self.weight.shape[1] or None, self._units)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """Batch normalization with functional running stats (reference:
    ``BatchNorm``; aux mutation handled per block.py design note)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, grad_req="null",
                allow_deferred_init=True)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, grad_req="null",
                allow_deferred_init=True)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if np.dtype(dtype).itemsize < 4:
            dtype = "float32"  # keep BN statistics in fp32 (AMP-safe)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        out, new_mean, new_var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var, eps=self._eps,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)
        if autograd.is_training() and not self._use_global_stats:
            self.running_mean.set_data(new_mean)
            self.running_var.set_data(new_var)
        return out


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BN (reference: ``contrib.nn.SyncBatchNorm``).

    Under pjit/shard_map data parallelism the batch statistics reduce over
    the mesh automatically when the batch axis is sharded, so this is the
    same op; kept as a distinct class for API parity.
    """

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._ngroups = num_groups
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._ngroups,
                           eps=self._eps)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        import mxnet_tpu.ndarray as F
        if isinstance(function, str):
            fn = getattr(F, function)
            self._func = lambda F_, *a: fn(*a)
        else:
            self._func = function

    def hybrid_forward(self, F, *args):
        return self._func(F, *args)
