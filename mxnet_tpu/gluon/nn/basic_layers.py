"""Basic Gluon layers (reference: ``python/mxnet/gluon/nn/basic_layers.py``)."""
from __future__ import annotations

import numpy as np

from ... import autograd
from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import shape_is_known
from .activations import Activation as _Activation


class Sequential(Block):
    """Imperative stack (reference: ``Sequential``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self._children[str(len(self._children))] = b

    def forward(self, x):
        for b in self._children.values():
            x = b(x)
        return x

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def hybridize(self, active=True, **kwargs):
        super().hybridize(active, **kwargs)


def _bn_relu_fusion_plan(children):
    """Pair each ``BatchNorm`` directly followed by a relu
    ``Activation`` for the fused kernel-tier op (docs/kernels.md).

    Returns ``[(block, fused)]`` where ``fused=True`` marks a BatchNorm
    whose trailing relu is folded into ``_forward_fused_relu`` (the
    Activation block is consumed).  Active only when the Pallas tier is
    armed (``MXNET_TPU_KERNELS=1``) -- the decision is read per forward
    and baked into each trace like every other static op param, so arm
    the tier before building/tracing the net."""
    from ...kernels import mode as _kernels_mode
    blocks = list(children)
    if _kernels_mode() != "on":
        return [(b, False) for b in blocks]
    plan = []
    i = 0
    while i < len(blocks):
        b = blocks[i]
        nxt = blocks[i + 1] if i + 1 < len(blocks) else None
        if type(b) in (BatchNorm, SyncBatchNorm) \
                and type(nxt) is _Activation \
                and getattr(nxt, "_act", None) == "relu":
            plan.append((b, True))
            i += 2
            continue
        plan.append((b, False))
        i += 1
    return plan


class HybridSequential(HybridBlock):
    """Compilable stack (reference: ``HybridSequential``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self._children[str(len(self._children))] = b

    def _forward_impl(self, x):
        for b, fused in _bn_relu_fusion_plan(self._children.values()):
            x = b._forward_fused_relu(x) if fused else b(x)
        return x

    def hybrid_forward(self, F, x):
        for b, fused in _bn_relu_fusion_plan(self._children.values()):
            x = b._forward_fused_relu(x) if fused else b(x)
        return x

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Fully-connected layer (reference: ``Dense``); weight (units,
    in_units), deferred in_units."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self._act:
            out = F.Activation(out, act_type=self._act)
        return out

    def __repr__(self):
        return "Dense(%s -> %s)" % (self.weight.shape[1] or None, self._units)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate <= 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """Batch normalization with functional running stats (reference:
    ``BatchNorm``; aux mutation handled per block.py design note)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, grad_req="null",
                allow_deferred_init=True)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, grad_req="null",
                allow_deferred_init=True)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if np.dtype(dtype).itemsize < 4:
            dtype = "float32"  # keep BN statistics in fp32 (AMP-safe)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        out, new_mean, new_var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var, eps=self._eps,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)
        if autograd.is_training() and not self._use_global_stats:
            self.running_mean.set_data(new_mean)
            self.running_var.set_data(new_var)
        return out

    def _forward_fused_relu(self, x):
        """BN+ReLU through the kernel tier's fused op -- the
        HybridSequential fusion-site entry (docs/kernels.md): a
        BatchNorm directly followed by a relu Activation dispatches
        here when MXNET_TPU_KERNELS=1, consuming the Activation.  Same
        running-stat contract as ``hybrid_forward``; works eagerly and
        under trace (``Parameter.data()`` yields the traced value
        inside ``functionalize``)."""
        from ...symbol.symbol import Symbol
        if isinstance(x, Symbol):
            from ... import symbol as F
            params = {k: p.var() for k, p in self._reg_params.items()}
            out, _nm, _nv = F.fused_batch_norm_relu(
                x, params["gamma"], params["beta"],
                params["running_mean"], params["running_var"],
                eps=self._eps, momentum=self._momentum,
                fix_gamma=not self._scale,
                use_global_stats=self._use_global_stats, axis=self._axis)
            return out
        from ... import ndarray as F
        from ..parameter import DeferredInitializationError
        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._infer_and_finish(x)
            params = {k: p.data() for k, p in self._reg_params.items()}
        out, new_mean, new_var = F.fused_batch_norm_relu(
            x, params["gamma"], params["beta"], params["running_mean"],
            params["running_var"], eps=self._eps,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)
        if autograd.is_training() and not self._use_global_stats:
            self.running_mean.set_data(new_mean)
            self.running_var.set_data(new_var)
        return out


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BN (reference: ``contrib.nn.SyncBatchNorm``).

    Under pjit/shard_map data parallelism the batch statistics reduce over
    the mesh automatically when the batch axis is sharded, so this is the
    same op; kept as a distinct class for API parity.
    """

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)


class Embedding(HybridBlock):
    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._ngroups = num_groups
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._ngroups,
                           eps=self._eps)


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        import mxnet_tpu.ndarray as F
        if isinstance(function, str):
            fn = getattr(F, function)
            self._func = lambda F_, *a: fn(*a)
        else:
            self._func = function

    def hybrid_forward(self, F, *args):
        return self._func(F, *args)
