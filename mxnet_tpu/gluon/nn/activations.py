"""Activation layers (reference: ``python/mxnet/gluon/nn/activations.py``)."""
from __future__ import annotations

from ..block import HybridBlock


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act = activation

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act)

    def __repr__(self):
        return "Activation(%s)" % self._act


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer="zeros", in_channels=1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F._prelu(x, alpha)


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)
