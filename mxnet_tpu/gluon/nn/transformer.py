"""Transformer layers: MultiHeadAttention, PositionwiseFFN, encoder cells.

TPU-native re-design of the attention stack the reference exposes through
``src/operator/contrib/transformer.cc`` (interleaved matmul kernels) and
GluonNLP's BERT blocks.  Layout is batch-major (batch, seq, units); heads
fold into the batch dimension so every matmul is a large MXU-friendly
``batch_dot``, and the score x value contraction can run through the
Pallas flash-attention kernel (``ops/pallas/flash_attention.py``) when no
padding mask is needed.
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock
from ..parameter import shape_is_known

__all__ = ["MultiHeadAttention", "PositionwiseFFN",
           "TransformerEncoderCell", "TransformerEncoder"]


class MultiHeadAttention(HybridBlock):
    """Self/cross multi-head attention (reference kernels:
    ``interleaved_matmul_selfatt_qk/valatt``).

    ``use_flash``: True = Pallas flash kernels (fwd + blockwise bwd;
    masked variant included), False = XLA path, None (default) = auto,
    Pallas on TPU backends when the sequence tiles evenly.  The masked
    XLA fallback (and the dropout>0 path) materializes masked scores
    per fusion tile.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 use_flash=None, causal=False, tp_mode=False,
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError("units %d not divisible by heads %d"
                             % (units, num_heads))
        self._units = units
        self._heads = num_heads
        self._dropout = dropout
        self._use_flash = use_flash
        self._causal = causal
        self._tp_mode = tp_mode
        with self.name_scope():
            if tp_mode:
                # separate q/k/v projections: each weight's OUTPUT dim
                # (u = heads*head_dim) column-shards cleanly over tp --
                # a fused (3u, in) weight cannot carry a [q|k|v]-wise
                # tp tiling as one NamedSharding, so slicing it sharded
                # would reshard at every q/k/v split
                for nm in ("query", "key", "value"):
                    setattr(self, nm + "_weight", self.params.get(
                        nm + "_weight", shape=(units, 0), dtype=dtype,
                        allow_deferred_init=True))
                    setattr(self, nm + "_bias", self.params.get(
                        nm + "_bias", shape=(units,), dtype=dtype,
                        init="zeros") if use_bias else None)
                self.qkv_weight = None
                self.qkv_bias = None
            else:
                self.qkv_weight = self.params.get(
                    "qkv_weight", shape=(3 * units, 0), dtype=dtype,
                    allow_deferred_init=True)
                if use_bias:
                    self.qkv_bias = self.params.get(
                        "qkv_bias", shape=(3 * units,), dtype=dtype,
                        init="zeros")
                else:
                    self.qkv_bias = None
            self.out_weight = self.params.get(
                "out_weight", shape=(units, units), dtype=dtype)
            if use_bias:
                self.out_bias = self.params.get(
                    "out_bias", shape=(units,), dtype=dtype, init="zeros")
            else:
                self.out_bias = None

    def infer_shape(self, x, *args):
        if self._tp_mode:
            for nm in ("query", "key", "value"):
                getattr(self, nm + "_weight").shape = \
                    (self._units, x.shape[-1])
        else:
            self.qkv_weight.shape = (3 * self._units, x.shape[-1])

    def shard_tp(self, mesh, axis="tp"):
        """Megatron sharding: q/k/v column-parallel (output dims over
        ``axis``), out row-parallel (input dim over ``axis``)."""
        from jax.sharding import PartitionSpec as P
        if not self._tp_mode:
            raise ValueError("build the attention with tp_mode=True "
                             "before sharding")
        for nm in ("query", "key", "value"):
            _tp_place(getattr(self, nm + "_weight"), mesh, P(axis, None))
            bias = getattr(self, nm + "_bias")
            if bias is not None:
                _tp_place(bias, mesh, P(axis))
        _tp_place(self.out_weight, mesh, P(None, axis))
        if self.out_bias is not None:
            _tp_place(self.out_bias, mesh, P())
        return self

    def hybrid_forward(self, F, x, mask=None, qkv_weight=None, qkv_bias=None,
                       out_weight=None, out_bias=None, query_weight=None,
                       query_bias=None, key_weight=None, key_bias=None,
                       value_weight=None, value_bias=None):
        b, seq, _ = x.shape
        h, hd = self._heads, self._units // self._heads
        if self._tp_mode:
            # tensor-parallel path: separate column-parallel q/k/v
            # projections, and heads stay a standalone dim (b, h, seq,
            # hd) so the head-dim sharding propagates through every
            # matmul (merging b*h would hide the sharded factor behind
            # the unsharded major dim and force an all-gather); one psum
            # appears only at the row-parallel output FC
            def proj4(w, bias):
                t = F.FullyConnected(x, w, bias, num_hidden=self._units,
                                     no_bias=bias is None, flatten=False)
                return t.reshape((b, seq, h, hd)).transpose((0, 2, 1, 3))
            q4 = proj4(query_weight, query_bias)
            k4 = proj4(key_weight, key_bias)
            v4 = proj4(value_weight, value_bias)
            scores = F.matmul(q4, k4.transpose((0, 1, 3, 2))) \
                * (1.0 / hd ** 0.5)
            if mask is not None:
                m = mask.reshape((b, 1, seq, seq))
                scores = F.where(m.broadcast_to((b, h, seq, seq)), scores,
                                 F.ones_like(scores) * -1e30)
            elif self._causal:
                # lower-triangular causal mask built from broadcast cmp
                idx = F.arange(0, seq)
                keep = idx.reshape((seq, 1)) >= idx.reshape((1, seq))
                scores = F.where(
                    keep.reshape((1, 1, seq, seq))
                        .broadcast_to((b, h, seq, seq)),
                    scores, F.ones_like(scores) * -1e30)
            att = F.softmax(scores, axis=-1)
            if self._dropout:
                att = F.Dropout(att, p=self._dropout)
            ctx4 = F.matmul(att, v4)
            out = ctx4.transpose((0, 2, 1, 3)).reshape(
                (b, seq, self._units))
            return F.FullyConnected(out, out_weight, out_bias,
                                    num_hidden=self._units,
                                    no_bias=out_bias is None,
                                    flatten=False)
        qkv = F.FullyConnected(x, qkv_weight, qkv_bias,
                               num_hidden=3 * self._units,
                               no_bias=qkv_bias is None, flatten=False)
        # (b, seq, 3u) -> q/k/v each (b*h, seq, hd)
        def heads_of(t):
            t = t.reshape((b, seq, h, hd)).transpose((0, 2, 1, 3))
            return t.reshape((b * h, seq, hd))
        q = heads_of(F.slice_axis(qkv, axis=2, begin=0, end=self._units))
        k = heads_of(F.slice_axis(qkv, axis=2, begin=self._units,
                                  end=2 * self._units))
        v = heads_of(F.slice_axis(qkv, axis=2, begin=2 * self._units,
                                  end=3 * self._units))
        from ... import autograd as _ag
        if mask is None:
            ctx_out = F.flash_attention(q, k, v, causal=self._causal,
                                        use_pallas=self._use_flash)
        elif not self._dropout or not _ag.is_training():
            # dropout only matters while training; inference with the
            # standard padding mask takes the flash path
            # masked flash path: the (b, seq, seq) padding mask rides
            # into the kernel; no (seq, seq) scores in HBM
            ctx_out = F.flash_attention_masked(
                q, k, v, mask.reshape((b, seq, seq)), heads=h,
                use_pallas=self._use_flash)
        else:
            scores = F.batch_dot(q, k, transpose_b=True) * (1.0 / hd ** 0.5)
            # mask: (b, seq_q, seq_k) with 1 = attend; broadcast over heads
            m = mask.reshape((b, 1, seq, seq)) \
                .broadcast_to((b, h, seq, seq)).reshape((b * h, seq, seq))
            scores = F.where(m, scores, F.ones_like(scores) * -1e30)
            att = F.softmax(scores, axis=-1)
            if self._dropout:
                att = F.Dropout(att, p=self._dropout)
            ctx_out = F.batch_dot(att, v)
        out = ctx_out.reshape((b, h, seq, hd)).transpose((0, 2, 1, 3)) \
            .reshape((b, seq, self._units))
        return F.FullyConnected(out, out_weight, out_bias,
                                num_hidden=self._units,
                                no_bias=out_bias is None, flatten=False)


def _tp_place(param, mesh, spec):
    from ...parallel.tensor_parallel import place_param
    place_param(param, mesh, spec)


class PositionwiseFFN(HybridBlock):
    """Feed-forward block (BERT intermediate+output)."""

    def __init__(self, units, hidden_size, activation="gelu", dropout=0.0,
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        from .basic_layers import Dense, Dropout
        self._dropout = dropout
        with self.name_scope():
            self.ffn_1 = Dense(hidden_size, activation=activation,
                               flatten=False, in_units=units, dtype=dtype)
            self.ffn_2 = Dense(units, flatten=False, in_units=hidden_size,
                               dtype=dtype)
            self.drop = Dropout(dropout)

    def shard_tp(self, mesh, axis="tp"):
        from jax.sharding import PartitionSpec as P
        _tp_place(self.ffn_1.weight, mesh, P(axis, None))
        if self.ffn_1.bias is not None:
            _tp_place(self.ffn_1.bias, mesh, P(axis))
        _tp_place(self.ffn_2.weight, mesh, P(None, axis))
        if self.ffn_2.bias is not None:
            _tp_place(self.ffn_2.bias, mesh, P())
        return self

    def hybrid_forward(self, F, x):
        return self.drop(self.ffn_2(self.ffn_1(x)))


class TransformerEncoderCell(HybridBlock):
    """Post-LN encoder cell (BERT style): LN(x + MHA(x)), LN(. + FFN(.))."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 use_flash=None, tp_mode=False, dtype="float32",
                 **kwargs):
        super().__init__(**kwargs)
        from .basic_layers import Dropout, LayerNorm
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads,
                                                dropout=dropout,
                                                use_flash=use_flash,
                                                tp_mode=tp_mode,
                                                dtype=dtype)
            self.attn_drop = Dropout(dropout)
            self.ln_1 = LayerNorm(in_channels=units)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout=dropout,
                                       dtype=dtype)
            self.ln_2 = LayerNorm(in_channels=units)

    def shard_tp(self, mesh, axis="tp"):
        from jax.sharding import PartitionSpec as P
        self.attention.shard_tp(mesh, axis)
        self.ffn.shard_tp(mesh, axis)
        for p in (self.ln_1, self.ln_2):
            for prm in p.collect_params().values():
                _tp_place(prm, mesh, P())
        return self

    def hybrid_forward(self, F, x, mask=None):
        att = self.attn_drop(self.attention(x, mask))
        x = self.ln_1(x + att)
        return self.ln_2(x + self.ffn(x))


class TransformerEncoder(HybridBlock):
    """Stack of encoder cells with learned positional embedding."""

    def __init__(self, units, hidden_size, num_layers, num_heads,
                 max_length=512, dropout=0.0, use_flash=None,
                 tp_mode=False, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        from .basic_layers import Dropout, LayerNorm
        self._max_length = max_length
        self._units = units
        with self.name_scope():
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units), dtype=dtype)
            self.drop = Dropout(dropout)
            self.ln = LayerNorm(in_channels=units)
            self.cells = []
            for i in range(num_layers):
                cell = TransformerEncoderCell(units, hidden_size, num_heads,
                                              dropout=dropout,
                                              use_flash=use_flash,
                                              tp_mode=tp_mode,
                                              dtype=dtype)
                setattr(self, "cell%d" % i, cell)
                self.cells.append(cell)

    def shard_tp(self, mesh, axis="tp"):
        from jax.sharding import PartitionSpec as P
        for cell in self.cells:
            cell.shard_tp(mesh, axis)
        _tp_place(self.position_weight, mesh, P())
        for prm in self.ln.collect_params().values():
            _tp_place(prm, mesh, P())
        return self

    def hybrid_forward(self, F, x, mask=None, position_weight=None):
        seq = x.shape[1]
        pos = F.slice_axis(position_weight, axis=0, begin=0, end=seq)
        x = x + pos.expand_dims(0)
        x = self.drop(self.ln(x))
        # each cell carries DISTINCT weights; a scan needs the per-layer
        # params stacked into one leading-axis pytree (a param-store
        # refactor, tracked under ROADMAP item 2's BERT work) -- until
        # then the unroll is deliberate and its compile cost accepted
        for cell in self.cells:  # mxlint: disable=python-loop-unroll
            x = cell(x, mask)
        return x
