"""``gluon.nn`` (reference: ``python/mxnet/gluon/nn/``)."""
from ..block import Block, HybridBlock, SymbolBlock
from .activations import (ELU, GELU, SELU, Activation, LeakyReLU, PReLU,
                          Swish)
from .basic_layers import (BatchNorm, Dense, Dropout, Embedding, Flatten,
                           GroupNorm, HybridLambda, HybridSequential,
                           InstanceNorm, Lambda, LayerNorm, Sequential,
                           SyncBatchNorm)
from .conv_layers import (AvgPool1D, AvgPool2D, AvgPool3D, Conv1D,
                          Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,
                          GlobalAvgPool1D, GlobalAvgPool2D, GlobalAvgPool3D,
                          GlobalMaxPool1D, GlobalMaxPool2D, GlobalMaxPool3D,
                          MaxPool1D, MaxPool2D, MaxPool3D, ReflectionPad2D)
from .transformer import (MultiHeadAttention, PositionwiseFFN,
                          TransformerEncoder, TransformerEncoderCell)
