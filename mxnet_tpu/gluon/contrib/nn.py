"""Contrib layers (reference: ``gluon/contrib/nn/basic_layers.py``)."""
from __future__ import annotations

from ..block import Block, HybridBlock
from .. import nn as _nn


class Concurrent(Block):
    """Parallel branches concatenated on ``axis`` (reference:
    ``Concurrent``)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for b in blocks:
            self._children[str(len(self._children))] = b

    def forward(self, x):
        from ... import ndarray as nd
        outs = [b(x) for b in self._children.values()]
        return nd.concat(*outs, dim=self.axis)


class HybridConcurrent(HybridBlock):
    """Compilable Concurrent (reference: ``HybridConcurrent``)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for b in blocks:
            self._children[str(len(self._children))] = b

    def hybrid_forward(self, F, x):
        outs = [b(x) for b in self._children.values()]
        return F.Concat(*outs, dim=self.axis)


class Identity(HybridBlock):
    """Pass-through (reference: ``Identity``) -- the residual-branch
    placeholder."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(_nn.Embedding):
    """Embedding with the row-sparse gradient INTENT (reference:
    ``SparseEmbedding``).  TPU-first note: gradients stay dense-tiled in
    the compiled step (see ``ndarray/sparse.py`` design note); the
    row-sparse win is realized on the kvstore/optimizer side via
    ``row_sparse_pull`` + row-sparse updates."""

    def __init__(self, input_dim, output_dim, dtype="float32", **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         sparse_grad=True, **kwargs)
