"""Loss blocks (reference: ``python/mxnet/gluon/loss.py``)."""
from __future__ import annotations

from ..base import MXNetError
from .block import HybridBlock


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, pred, label):
    return label.reshape(pred.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SoftmaxCrossEntropyLoss(Loss):
    """Reference: ``SoftmaxCrossEntropyLoss`` -- the canonical classifier
    loss (BASELINE configs 1-2)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, pred, label)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, pred, label)
        if not self._from_sigmoid:
            # log-sum-exp stable BCE with logits
            loss = F.relu(pred) - pred * label + \
                F.Activation(-F.abs(pred), act_type="softrelu")
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label +
                     F.log(1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        if self._label_format == "binary":
            label = 2 * label - 1
        loss = F.Activation(-pred * label, act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, anchor, positive, negative, sample_weight=None):
        loss = F.sum(F.square(anchor - positive) - F.square(anchor - negative),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        num = F.sum(input1 * input2, axis=-1)
        denom = F.sqrt(F.sum(F.square(input1), axis=-1)) * \
            F.sqrt(F.sum(F.square(input2), axis=-1))
        cos = num / (denom + 1e-12)
        pos = 1.0 - cos
        neg = F.relu(cos - self._margin)
        label = label.reshape(cos.shape)
        loss = F.where(label == 1, pos, neg)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification (reference: ``CTCLoss``;
    ``src/operator/contrib/ctc_loss.cc``).  Uses the standard
    alpha-recursion in log space via lax.scan."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        import jax.numpy as jnp
        from jax import lax
        from ..ndarray import NDArray

        logits = pred._data if isinstance(pred, NDArray) else pred
        labels = label._data if isinstance(label, NDArray) else label
        if self._layout == "TNC":
            logits = jnp.swapaxes(logits, 0, 1)
        B, T, V = logits.shape
        L = labels.shape[1]
        import jax
        logp = jax.nn.log_softmax(logits, axis=-1)
        blank = 0
        labels_i = labels.astype(jnp.int32)
        # extended label seq: blank, l1, blank, l2, ... blank  (len 2L+1);
        # negative labels are padding (reference convention) and map to
        # blank so they cannot emit
        ext = jnp.full((B, 2 * L + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(jnp.where(labels_i >= 0, labels_i,
                                            blank))
        S = 2 * L + 1
        neg_inf = -1e30
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0])

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        if pred_lengths is not None:
            pl = (pred_lengths._data if isinstance(pred_lengths, NDArray)
                  else pred_lengths).astype(jnp.int32)
        else:
            pl = jnp.full((B,), T, jnp.int32)

        def step(alpha, t):
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            m = jnp.maximum(jnp.maximum(alpha, a_shift1), a_shift2)
            m_safe = jnp.where(m <= neg_inf / 2, 0.0, m)
            summed = jnp.exp(alpha - m_safe) + jnp.exp(a_shift1 - m_safe) \
                + jnp.exp(a_shift2 - m_safe)
            summed = jnp.where(m <= neg_inf / 2, 0.0, summed)
            newa = m_safe + jnp.log(jnp.maximum(summed, 1e-37))
            newa = jnp.where(m <= neg_inf / 2, neg_inf, newa)
            emit = jnp.take_along_axis(logp[:, t, :], ext, axis=1)
            # Padded timesteps (t >= pred_length) carry alpha unchanged so
            # the final read-off sees each sample's own last valid step.
            active = (t < pl)[:, None]
            return jnp.where(active, newa + emit, alpha), None

        alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
        if label_lengths is not None:
            ll = (label_lengths._data if isinstance(label_lengths, NDArray)
                  else label_lengths).astype(jnp.int32)
        else:
            # infer per-sample length from -1 padding (reference
            # behavior when no explicit label_lengths is given)
            ll = jnp.sum(labels_i >= 0, axis=1).astype(jnp.int32)
        endpos = 2 * ll  # index of final blank
        last1 = jnp.take_along_axis(alpha, endpos[:, None], axis=1)[:, 0]
        last2 = jnp.take_along_axis(alpha, jnp.maximum(endpos - 1, 0)[:, None],
                                    axis=1)[:, 0]
        # an empty label sequence has only the all-blank path: the
        # endpos-1 clamp would read alpha[:,0] twice (double count)
        last2 = jnp.where(ll == 0, neg_inf, last2)
        m = jnp.maximum(last1, last2)
        ll_total = m + jnp.log(jnp.exp(last1 - m) + jnp.exp(last2 - m))
        from ..ndarray import from_jax
        return from_jax(-ll_total)
