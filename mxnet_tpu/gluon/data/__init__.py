"""``gluon.data`` (reference: ``python/mxnet/gluon/data/``)."""
from .dataloader import DataLoader
from .dataset import (ArrayDataset, Dataset, RecordFileDataset,
                      SimpleDataset)
from .sampler import (BatchSampler, IntervalSampler, RandomSampler, Sampler,
                      SequentialSampler)
from . import vision
