"""Vision transforms (reference:
``python/mxnet/gluon/data/vision/transforms.py``).  Operate on HWC uint8
or float NDArrays host-side (numpy), like the reference's cpu augment path.
"""
from __future__ import annotations

import numpy as np

from ....ndarray import NDArray, array
from ...block import Block, HybridBlock
from ...nn.basic_layers import Sequential, HybridSequential


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class Compose(Sequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: ``ToTensor``)."""

    def forward(self, x):
        a = _to_np(x).astype(np.float32) / 255.0
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        elif a.ndim == 4:
            a = a.transpose(0, 3, 1, 2)
        return array(a)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32)
        self._std = np.asarray(std, np.float32)

    def forward(self, x):
        a = _to_np(x)
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return array((a - mean) / std)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return array(_to_np(x).astype(self._dtype))


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        a = _to_np(x)
        w, h = self._size
        out = jax.image.resize(jnp.asarray(a, jnp.float32),
                               (h, w, a.shape[2]), "bilinear")
        if a.dtype == np.uint8:
            out = jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)
        return NDArray(out)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        a = _to_np(x)
        w, h = self._size
        y0 = max((a.shape[0] - h) // 2, 0)
        x0 = max((a.shape[1] - w) // 2, 0)
        return array(a[y0:y0 + h, x0:x0 + w])


class RandomResizedCrop(Block):
    """Random area/aspect crop + resize (reference: ``RandomResizedCrop``,
    the ImageNet train-time augmentation of BASELINE config 2)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        a = _to_np(x)
        H, W = a.shape[:2]
        area = H * W
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            log_ratio = (np.log(self._ratio[0]), np.log(self._ratio[1]))
            aspect = np.exp(np.random.uniform(*log_ratio))
            w = int(round(np.sqrt(target_area * aspect)))
            h = int(round(np.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                x0 = np.random.randint(0, W - w + 1)
                y0 = np.random.randint(0, H - h + 1)
                crop = a[y0:y0 + h, x0:x0 + w]
                return Resize(self._size)(array(crop))
        return Resize(self._size)(CenterCrop(min(H, W))(array(a)))


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._pad = pad

    def forward(self, x):
        a = _to_np(x)
        if self._pad:
            p = self._pad
            a = np.pad(a, ((p, p), (p, p), (0, 0)), mode="constant")
        w, h = self._size
        y0 = np.random.randint(0, max(a.shape[0] - h, 0) + 1)
        x0 = np.random.randint(0, max(a.shape[1] - w, 0) + 1)
        return array(a[y0:y0 + h, x0:x0 + w])


class RandomFlipLeftRight(Block):
    def forward(self, x):
        a = _to_np(x)
        if np.random.rand() < 0.5:
            a = a[:, ::-1]
        return array(np.ascontiguousarray(a))


class RandomFlipTopBottom(Block):
    def forward(self, x):
        a = _to_np(x)
        if np.random.rand() < 0.5:
            a = a[::-1]
        return array(np.ascontiguousarray(a))


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        a = _to_np(x).astype(np.float32)
        f = 1.0 + np.random.uniform(-self._b, self._b)
        return array(np.clip(a * f, 0, 255))


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        a = _to_np(x).astype(np.float32)
        f = 1.0 + np.random.uniform(-self._c, self._c)
        mean = a.mean()
        return array(np.clip((a - mean) * f + mean, 0, 255))


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        a = _to_np(x).astype(np.float32)
        f = 1.0 + np.random.uniform(-self._s, self._s)
        gray = a.mean(axis=2, keepdims=True)
        return array(np.clip(gray + (a - gray) * f, 0, 255))


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))

    def forward(self, x):
        for t in np.random.permutation(self._ts).tolist():
            x = t(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA noise (reference: ``RandomLighting``)."""

    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha_std=0.05):
        super().__init__()
        self._std = alpha_std

    def forward(self, x):
        a = _to_np(x).astype(np.float32)
        alpha = np.random.normal(0, self._std, 3).astype(np.float32)
        rgb = (self._eigvec @ (alpha * self._eigval)).astype(np.float32)
        return array(np.clip(a + rgb, 0, 255))
