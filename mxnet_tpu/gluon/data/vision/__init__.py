from . import transforms
from .datasets import (CIFAR10, CIFAR100, MNIST, FashionMNIST,
                       ImageFolderDataset, ImageRecordDataset)
