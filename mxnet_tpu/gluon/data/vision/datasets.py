"""Vision datasets (reference:
``python/mxnet/gluon/data/vision/datasets.py``).

Zero-egress note: the reference downloads MNIST/CIFAR from S3.  This
environment has no network, so each dataset reads the standard on-disk
format from ``root`` if present and otherwise falls back to a
deterministic synthetic sample of the same shape/dtype (flagged via
``.synthetic``), so end-to-end training paths stay runnable.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
import warnings

import numpy as np

from ....ndarray import array
from ..dataset import ArrayDataset, Dataset, RecordFileDataset


def _synthetic_images(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    data = (rng.rand(n, *shape) * 255).astype(np.uint8)
    label = rng.randint(0, num_classes, n).astype(np.int32)
    return data, label


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self.synthetic = False
        self._root = os.path.expanduser(root)
        self._get_data()

    def __getitem__(self, idx):
        x = array(self._data[idx])
        y = self._label[idx]
        if self._transform is not None:
            return self._transform(x, y)
        return x, y

    def __len__(self):
        return len(self._label)


class MNIST(_DownloadedDataset):
    """MNIST (reference: ``MNIST``); reads idx-ubyte files from root."""

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        base = "train" if self._train else "t10k"
        img = os.path.join(self._root, "%s-images-idx3-ubyte" % base)
        lbl = os.path.join(self._root, "%s-labels-idx1-ubyte" % base)
        for ext in ("", ".gz"):
            if os.path.exists(img + ext) and os.path.exists(lbl + ext):
                op = gzip.open if ext else open
                with op(lbl + ext, "rb") as f:
                    struct.unpack(">II", f.read(8))
                    label = np.frombuffer(f.read(), np.uint8).astype(np.int32)
                with op(img + ext, "rb") as f:
                    _, n, h, w = struct.unpack(">IIII", f.read(16))
                    data = np.frombuffer(f.read(), np.uint8) \
                        .reshape(n, h, w, 1)
                self._data, self._label = data, label
                return
        warnings.warn("MNIST files not found under %s and no network; "
                      "using deterministic synthetic data" % self._root)
        self.synthetic = True
        n = 60000 if self._train else 10000
        self._data, self._label = _synthetic_images(
            n, (28, 28, 1), 10, seed=42 if self._train else 43)


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 (reference: ``CIFAR10``); reads the python pickle batches."""

    _nclass = 10

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _load_batches(self, names):
        data, label = [], []
        for name in names:
            path = None
            for cand in (os.path.join(self._root, name),
                         os.path.join(self._root, "cifar-10-batches-py", name)):
                if os.path.exists(cand):
                    path = cand
                    break
            if path is None:
                return None, None
            with open(path, "rb") as f:
                d = pickle.load(f, encoding="latin1")
            data.append(np.asarray(d["data"], np.uint8)
                        .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            key = "labels" if "labels" in d else "fine_labels"
            label.append(np.asarray(d[key], np.int32))
        return np.concatenate(data), np.concatenate(label)

    def _get_data(self):
        names = ["data_batch_%d" % i for i in range(1, 6)] if self._train \
            else ["test_batch"]
        data, label = self._load_batches(names)
        if data is None:
            warnings.warn("CIFAR10 files not found under %s and no network; "
                          "using deterministic synthetic data" % self._root)
            self.synthetic = True
            n = 50000 if self._train else 10000
            data, label = _synthetic_images(
                n, (32, 32, 3), self._nclass, seed=44 if self._train else 45)
        self._data, self._label = data, label


class CIFAR100(CIFAR10):
    _nclass = 100

    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 fine_label=False, transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        names = ["train"] if self._train else ["test"]
        data, label = self._load_batches(names)
        if data is None:
            warnings.warn("CIFAR100 files not found; synthetic fallback")
            self.synthetic = True
            n = 50000 if self._train else 10000
            data, label = _synthetic_images(
                n, (32, 32, 3), 100, seed=46 if self._train else 47)
        self._data, self._label = data, label


class ImageRecordDataset(RecordFileDataset):
    """Images in RecordIO (reference: ``ImageRecordDataset``)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack_img
        record = super().__getitem__(idx)
        header, img = unpack_img(record)
        label = header.label
        img = array(img)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """Folder-per-class image tree (reference: ``ImageFolderDataset``)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = (".jpg", ".jpeg", ".png", ".bmp", ".npy")
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith(self._exts):
                    self.items.append((os.path.join(path, fname), label))

    def __getitem__(self, idx):
        from ....image import imread
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = array(np.load(path))
        else:
            img = imread(path, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
