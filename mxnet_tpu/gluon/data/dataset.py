"""Datasets (reference: ``python/mxnet/gluon/data/dataset.py``)."""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray import NDArray


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return _FilteredDataset(self, fn)

    def take(self, count):
        return _TakenDataset(self, count)

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _FilteredDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._indices = [i for i in range(len(data)) if fn(data[i])]

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, idx):
        return self._data[self._indices[idx]]


class _TakenDataset(Dataset):
    def __init__(self, data, count):
        self._data = data
        self._count = min(count, len(data))

    def __len__(self):
        return self._count

    def __getitem__(self, idx):
        if idx >= self._count:
            raise IndexError(idx)
        return self._data[idx]


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of equal-length arrays (reference: ``ArrayDataset``)."""

    def __init__(self, *args):
        assert args, "needs at least 1 array"
        self._length = len(args[0])
        self._data = []
        for a in args:
            if len(a) != self._length:
                raise MXNetError("all arrays must have the same length")
            self._data.append(a)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over an indexed RecordIO file (reference:
    ``RecordFileDataset`` -> ``recordio.py :: MXIndexedRecordIO``)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        idx_file = filename[:filename.rindex(".")] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
