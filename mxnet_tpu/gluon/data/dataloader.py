"""DataLoader (reference: ``python/mxnet/gluon/data/dataloader.py``).

TPU-native design notes: the reference forks multiprocessing workers and
ships batches through POSIX-shm cpu_shared NDArrays
(``src/storage/cpu_shared_storage_manager.h``).  Here workers are a
thread pool doing numpy-side decode/augment (the GIL is released inside
numpy/PIL/jax host ops), batches stay host-side numpy until
``as_in_context`` triggers one async host->device DMA -- overlap with
compute comes from PJRT async dispatch, replacing the engine-ordered copy.
A prefetch queue of ``prefetch`` batches double-buffers the device.
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

import jax

from ... import sync as _sync
from ... import telemetry as _telemetry
from ...base import MXNetError
from ...ndarray import NDArray, array
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "host_batchify_fn"]


def _host_stack(data):
    """Stack NDArray samples via ONE batched device fetch.

    A per-sample ``asnumpy`` is a blocking device->host round-trip (and
    a host-sync telemetry hit) for every element of the batch; a single
    ``jax.device_get`` over all samples fetches them in one bulk
    operation."""
    return np.stack(jax.device_get([d._data for d in data]))


def default_batchify_fn(data):
    """Stack samples into a batch (reference: ``default_batchify_fn``)."""
    if isinstance(data[0], NDArray):
        return array(_host_stack(data))
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return array(arr)


def host_batchify_fn(data):
    """Batchify that stays host-side numpy in the samples' compact dtype
    (uint8 stays uint8) -- the device-feed path's default, so the ONLY
    host->device transfer is the feed's async staging."""
    if isinstance(data[0], NDArray):
        return _host_stack(data)
    if isinstance(data[0], (tuple, list)):
        return tuple(host_batchify_fn(list(x)) for x in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120, ctx=None, mesh=None,
                 sharding=None, device_transform=None, feed_depth=None):
        self._dataset = dataset
        self._timeout = timeout
        # device-feed path (docs/data_pipeline.md): with a ctx/mesh/
        # sharding, batches stay host numpy through batchify and a
        # dataio.DeviceFeed stages them asynchronously; iteration then
        # yields device-resident batches
        self._feed_kw = None
        if ctx is not None or mesh is not None or sharding is not None:
            self._feed_kw = dict(ctx=ctx, mesh=mesh, sharding=sharding,
                                 transform=device_transform,
                                 depth=feed_depth)
            if batchify_fn is None:
                batchify_fn = host_batchify_fn
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when no batch_sampler")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle and sampler are mutually exclusive")
            batch_sampler = BatchSampler(sampler, batch_size,
                                        last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def __len__(self):
        return len(self._batch_sampler)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        it = self._iter_impl()
        if not _telemetry._ENABLED:
            yield from it
            return
        # starvation probe: time the consumer spends waiting on each
        # batch.  When data.wait_time rivals trainer.step_time, the
        # input pipeline -- not the device -- is the bottleneck.
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            _telemetry.hooks.dataloader_wait(time.perf_counter() - t0)
            yield batch

    def _iter_impl(self):
        if self._feed_kw is not None:
            yield from self._device_feed_iter()
            return
        yield from self._host_iter()

    def _host_iter(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        yield from self._threaded_iter()

    def _device_feed_iter(self):
        """Stage every host batch through a DeviceFeed; single-component
        batches unwrap to the bare NDArray for host-path parity."""
        from ...dataio import DeviceFeed
        feed = DeviceFeed(self._host_iter(), **self._feed_kw)
        try:
            for batch in feed:
                yield batch.data if len(batch) == 1 else batch
        finally:
            feed.close()

    def _threaded_iter(self):
        """Ordered thread-pool pipeline with bounded prefetch."""
        batches = list(self._batch_sampler)
        results = {}
        results_lock = _sync.Lock(name="dataloader.results")
        results_ready = _sync.Condition(results_lock,
                                        name="dataloader.results_ready")
        # Prefetch bound: decoded-but-unconsumed batches never exceed this,
        # so memory stays O(prefetch), not O(dataset).
        prefetch = max(self._prefetch, 1)
        work = queue.Queue()
        for i, b in enumerate(batches):
            work.put((i, b))
        stop = _sync.Event(name="dataloader.stop")
        next_wanted = [0]

        def worker():
            while not stop.is_set():
                try:
                    i, indices = work.get_nowait()
                except queue.Empty:
                    return
                with results_ready:
                    while (not stop.is_set()
                           and i >= next_wanted[0] + prefetch):
                        results_ready.wait(0.1)
                if stop.is_set():
                    return
                try:
                    out = self._make_batch(indices)
                except Exception as e:  # propagate to consumer
                    out = e
                with results_ready:
                    results[i] = out
                    results_ready.notify_all()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        try:
            for i in range(len(batches)):
                deadline = (time.monotonic() + self._timeout
                            if self._timeout else None)
                with results_ready:
                    next_wanted[0] = i
                    results_ready.notify_all()
                    while i not in results:
                        remaining = (deadline - time.monotonic()
                                     if deadline else None)
                        if remaining is not None and remaining <= 0:
                            raise MXNetError(
                                "DataLoader worker timed out after %ss "
                                "waiting for batch %d" % (self._timeout, i))
                        results_ready.wait(remaining if remaining is not None
                                           else 1.0)
                    out = results.pop(i)
                    results_ready.notify_all()
                if isinstance(out, Exception):
                    raise out
                yield out
        finally:
            stop.set()
            with results_ready:
                results_ready.notify_all()
