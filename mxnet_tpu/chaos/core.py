"""Deterministic fault injection: fail points, rules, actions.

A **fail point** is a named hook compiled into a subsystem's dangerous
spot -- checkpoint commit, the serving dispatch path, the hot-swap
install, the preemption signal handler::

    from .. import chaos as _chaos
    ...
    _chaos.fail_point("checkpoint.commit.pre_manifest", step=step)

Disarmed (the default and the production state), every fail point is a
single module-flag check -- the same zero-overhead contract as
``telemetry._ENABLED``.  Armed (``chaos.arm(seed)`` or a
``chaos.scenario(seed=...)`` block), each hit consults the injection
**rules** registered with :func:`on` and fires the rule's **action**:

- ``chaos.RAISE`` -- raise :class:`ChaosInjected` at the fail point
  (a crashing writer, a failing compiled call);
- ``chaos.KILL`` -- ``os._exit(137)``, the SIGKILL-shaped death that
  leaves whatever bytes happen to be on disk (no atexit, no flush);
- ``chaos.sleep(s)`` -- stall the hitting thread (a slow device, a
  wedged dispatch -- how the flood scenario holds the batcher worker);
- ``chaos.truncate(fname, keep=n)`` -- tear a file named in the fail
  point's context directory (the on-disk state a non-atomic writer or
  bit-rot leaves);
- any callable ``action(ctx)`` -- ``ctx`` carries the fail point's
  keyword context plus ``point``.

Determinism: rules fire on exact hit counts (``nth=3``, ``nth=(1, 2)``)
or on a per-rule ``random.Random`` seeded from ``(seed, point, index)``
(``prob=0.3``) -- a scenario replays identically for a fixed seed, so a
chaos failure in CI is reproducible at the shell.

Every fire is counted (``chaos.injected`` / ``chaos.injected.<point>``
plus a ``chaos.inject`` event) and every *tolerated* fault -- injected
or real weather -- is recorded by the recovery paths themselves via
:func:`survived` (``chaos.survived.<point>``): the quarantine of a torn
checkpoint, a retried async write, a hot-swap rollback, a suppressed
re-entrant SIGTERM.  ``chaos.stats()`` mirrors both locally so tests
can assert without telemetry armed.
"""
from __future__ import annotations

import contextlib
import json
import os
import random
import time

from .. import sync as _sync
from .. import telemetry as _telemetry
from ..base import MXNetError

__all__ = [
    "ChaosInjected", "arm", "disarm", "armed", "reset", "on",
    "fail_point", "survived", "stats", "scenario",
    "arm_from_spec", "make_spec",
    "RAISE", "KILL", "sleep", "truncate",
]

# THE flag every fail point checks (one module-attribute read).  Armed
# only by arm()/scenario() -- never by env var alone, so production
# processes cannot be chaos'd by a stray environment.
_ARMED = False

RAISE = "raise"
KILL = "kill"


class ChaosInjected(MXNetError):
    """The fault a ``chaos.RAISE`` rule injects at a fail point
    (``point`` names it, so recovery paths can pair their survival
    count with the exact site that made the weather)."""

    def __init__(self, msg, point=None):
        super().__init__(msg)
        self.point = point


def sleep(seconds):
    """Action: stall the thread hitting the fail point."""
    def _sleep(ctx):
        time.sleep(seconds)
    _sleep.chaos_label = "sleep(%gs)" % seconds
    return _sleep


def truncate(fname, keep=8):
    """Action: tear ``fname`` inside the fail point's context ``path``
    (a directory) down to ``keep`` bytes -- the torn-write state the
    manifest verification exists to catch."""
    def _truncate(ctx):
        path = ctx.get("path")
        if path is None:
            raise MXNetError("chaos.truncate needs a fail point that "
                             "passes path= context (got %r)" % (ctx,))
        target = os.path.join(path, fname) if os.path.isdir(path) else path
        with open(target, "r+b") as f:
            f.truncate(keep)
    _truncate.chaos_label = "truncate(%s)" % fname
    return _truncate


class _Rule:
    __slots__ = ("point", "action", "nth", "prob", "times",
                 "hits", "fired", "rng")

    def __init__(self, point, action, nth, prob, times, seed, index):
        self.point = point
        self.action = action
        self.nth = (frozenset((nth,)) if isinstance(nth, int)
                    else frozenset(nth) if nth is not None else None)
        self.prob = prob
        self.times = times
        self.hits = 0
        self.fired = 0
        # per-rule independent stream: deterministic for a fixed seed
        # regardless of what other rules (or the global RNG) consume
        self.rng = random.Random("%s:%s:%d" % (seed, point, index))

    def should_fire(self):
        """Called under the registry lock with ``hits`` already
        incremented for this visit."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self.nth is not None:
            return self.hits in self.nth
        if self.prob is not None:
            return self.rng.random() < self.prob
        return True

    def label(self):
        a = self.action
        if isinstance(a, str):
            return a
        return getattr(a, "chaos_label", getattr(a, "__name__", "call"))


_lock = _sync.Lock(name="chaos.rules")
_rules = {}        # point -> [_Rule]
_hits = {}         # point -> hit count (armed only)
_injected = {}     # point -> fire count
_survived = {}     # point -> survive count
_seed = None


def arm(seed=None):
    """Arm the fail points.  ``seed`` defaults to
    ``MXNET_TPU_CHAOS_SEED``; rules registered after ``arm`` draw their
    probability streams from it."""
    global _ARMED, _seed
    if seed is None:
        from .. import env as _env
        seed = _env.get("MXNET_TPU_CHAOS_SEED")
    with _lock:
        _seed = seed
    _ARMED = True


def disarm():
    """Disarm every fail point (rules and stats are kept for
    post-mortem assertions until :func:`reset`)."""
    global _ARMED
    _ARMED = False


def armed():
    return _ARMED


def reset():
    """Drop all rules and stats (does not change the armed flag)."""
    with _lock:
        _rules.clear()
        _hits.clear()
        _injected.clear()
        _survived.clear()


def on(point, action=RAISE, nth=None, prob=None, times=None):
    """Register an injection rule for ``point``.

    - ``nth``: fire on exactly these 1-based hit counts (int or
      iterable of ints);
    - ``prob``: fire on each hit with this probability (seeded,
      deterministic per rule);
    - ``times``: cap the number of fires (None = bounded only by
      ``nth``/``prob``);
    - neither ``nth`` nor ``prob``: fire on every hit (up to
      ``times``).
    """
    if nth is not None and prob is not None:
        raise MXNetError("chaos.on: nth= and prob= are exclusive")
    with _lock:
        seed = _seed if _seed is not None else 0
        rule = _Rule(point, action, nth, prob, times, seed,
                     len(_rules.get(point, ())))
        _rules.setdefault(point, []).append(rule)
    return rule


def fail_point(name, **ctx):
    """The hook a subsystem compiles into its dangerous spot.  Disarmed
    (default): one flag check, nothing else.  Armed: consult the rules
    for ``name`` and perform the matched action."""
    if not _ARMED:
        return
    _visit(name, ctx)


def _visit(name, ctx):
    fire = None
    with _lock:
        _hits[name] = _hits.get(name, 0) + 1
        for rule in _rules.get(name, ()):
            rule.hits += 1
            if fire is None and rule.should_fire():
                rule.fired += 1
                fire = rule
        if fire is not None:
            _injected[name] = _injected.get(name, 0) + 1
    if fire is None:
        return
    label = fire.label()
    if _telemetry._ENABLED:
        _telemetry.hooks.chaos_inject(name, label)
    action = fire.action
    if action == RAISE:
        raise ChaosInjected("chaos: injected fault at %r (hit %d)"
                            % (name, fire.hits), point=name)
    if action == KILL:
        # last act before the SIGKILL-shaped death: mark the flight
        # recorder (injected point + in-flight trace) and msync -- the
        # postmortem the blackbox CLI renders.  os._exit skips atexit
        # and every buffered sink; the mmap ring is all that survives.
        from .. import obs as _obs
        _obs.flight.emergency_dump("chaos.kill", point=name,
                                   hit=fire.hits)
        os._exit(137)           # SIGKILL-shaped: no atexit, no flush
    action(dict(ctx, point=name))


def survived(point, how=None):
    """Record a tolerated fault at ``point`` -- called by the recovery
    paths themselves (quarantine, write retry, swap rollback, re-entrant
    signal suppression), so survival is counted whether the fault was
    injected or real weather."""
    with _lock:
        _survived[point] = _survived.get(point, 0) + 1
    if _telemetry._ENABLED:
        _telemetry.hooks.chaos_survive(point, how)


def stats():
    """Local mirror of the chaos counters:
    ``{"hits": {...}, "injected": {...}, "survived": {...}}``."""
    with _lock:
        return {"hits": dict(_hits), "injected": dict(_injected),
                "survived": dict(_survived)}


@contextlib.contextmanager
def scenario(seed=0):
    """One deterministic chaos scenario: clears previous rules, arms
    with ``seed``, disarms on exit (stats survive until the next
    scenario/reset, so assertions can run after the block)."""
    reset()
    arm(seed)
    try:
        yield
    finally:
        disarm()


# ----------------------------------------------------------------------
# Cross-process chaos (ISSUE 15): a scenario serialized for launched
# ranks.  The launcher (a test, CI's chaos_dist stage) builds a spec
# with make_spec() and ships it in MXNET_TPU_CHAOS_SPEC; each worker
# replays it with arm_from_spec() -- an EXPLICIT harness call, so a
# production process with the variable in its environment stays inert
# (the same env-inert contract as arm()).  Rules can be scoped to one
# launcher rank and one supervisor generation, so "KILL rank 1 between
# the written and committed barriers, generation 0 only" is one JSON
# line replayed identically by every rank of every relaunch.
# ----------------------------------------------------------------------

def make_spec(seed=0, rules=()):
    """Serialize a chaos scenario for cross-process replay.  Each rule
    is a dict: ``point`` (required), ``action`` (``"raise"`` (default),
    ``"kill"``, ``{"sleep": seconds}``, or ``{"truncate": {"fname": f,
    "keep": n}}``), ``nth``/``prob``/``times`` as in :func:`on`, plus
    ``rank`` / ``generation`` scoping (omit = every rank / every
    generation)."""
    spec = {"seed": seed, "rules": [dict(r) for r in rules]}
    for rule in spec["rules"]:
        _spec_action(rule.get("action", RAISE))   # validate early
        if "point" not in rule:
            raise MXNetError("chaos spec rule without a point: %r"
                             % (rule,))
    return json.dumps(spec, sort_keys=True)


def _spec_action(action):
    """Deserialize one spec action into what :func:`on` takes."""
    if action in (RAISE, KILL):
        return action
    if isinstance(action, dict) and len(action) == 1:
        if "sleep" in action:
            return sleep(float(action["sleep"]))
        if "truncate" in action:
            t = action["truncate"]
            if isinstance(t, str):
                return truncate(t)
            return truncate(t["fname"], keep=int(t.get("keep", 8)))
    raise MXNetError("chaos spec: unknown action %r (want 'raise', "
                     "'kill', {'sleep': s} or {'truncate': ...})"
                     % (action,))


def arm_from_spec(spec=None, rank=None, generation=None):
    """Arm this process from a serialized rule spec -- the multi-rank
    test harness's EXPLICIT opt-in.  ``spec`` defaults to the
    ``MXNET_TPU_CHAOS_SPEC`` environment variable; absent/empty returns
    False without arming anything.  ``rank`` defaults to
    ``MXNET_TPU_PROC_ID`` and ``generation`` to
    ``MXNET_TPU_GENERATION``; rules scoped to another rank/generation
    are skipped, so one spec drives a whole launched world across
    supervisor relaunches.  Clears previous rules, then arms with the
    spec's seed (rules replay deterministically per rank)."""
    if spec is None:
        spec = os.environ.get("MXNET_TPU_CHAOS_SPEC", "")
    if isinstance(spec, (bytes, str)):
        if not spec.strip():
            return False
        spec = json.loads(spec)
    if rank is None:
        rank = _env_int("MXNET_TPU_PROC_ID")
    if generation is None:
        generation = _env_int("MXNET_TPU_GENERATION")
    reset()
    arm(spec.get("seed", 0))
    for rule in spec.get("rules", ()):
        if rule.get("rank") is not None and int(rule["rank"]) != rank:
            continue
        if rule.get("generation") is not None \
                and int(rule["generation"]) != generation:
            continue
        nth = rule.get("nth")
        if isinstance(nth, list):
            nth = tuple(nth)
        on(rule["point"], action=_spec_action(rule.get("action", RAISE)),
           nth=nth, prob=rule.get("prob"), times=rule.get("times"))
    return True


def _env_int(name):
    try:
        return int(os.environ.get(name, "0") or 0)
    except ValueError:
        return 0
