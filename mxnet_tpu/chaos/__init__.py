"""Chaos harness: deterministic fault injection for the always-on loop
(ISSUE 12).

Every robustness mechanism in the tree -- atomic checkpoint commits
with corruption-tolerant discovery, the draining hot-swap registry,
async-write retries, preemption saves, batcher load-shedding -- existed
without anything ever *injecting* the fault it guards against.  This
package is the weather machine:

- **fail points** (``chaos.fail_point(name)``, ``core.py``): named
  hooks compiled into the dangerous spots (checkpoint commit, serving
  dispatch, the hot-swap install, the preemption signal path).
  Disarmed they are one module-flag check; armed, seeded rules decide
  deterministically which hit dies, and how (``RAISE``, ``KILL``,
  ``sleep``, ``truncate``, any callable);
- **scenarios** (``scenarios.py``): the composed experiments tests,
  CI's ``chaos`` stage, and ``bench_serving_hotswap`` share --
  continuous-train -> hot-swap under client load (with an optional
  torn publish), and a flood past the bounded serving queue;
- **cross-process replay** (ISSUE 15): ``make_spec()`` serializes a
  seeded scenario into ``MXNET_TPU_CHAOS_SPEC`` and launched ranks
  replay it with the EXPLICIT ``arm_from_spec()`` call (rules scoped
  per rank and per supervisor generation; production stays env-inert);
- **accounting**: every injected fault counts
  (``chaos.injected.<point>``) and every tolerated one -- injected or
  real -- is recorded by the recovery path itself
  (``chaos.survived.<point>``), so "we survived N faults" is a
  queryable claim, not a vibe.

Fail-point catalogue, seeding rules, and how to add a point:
``docs/chaos.md``.
"""
from __future__ import annotations

from .core import (KILL, RAISE, ChaosInjected, arm, arm_from_spec,
                   armed, disarm, fail_point, make_spec, on, reset,
                   scenario, sleep, stats, survived, truncate)

__all__ = [
    "ChaosInjected", "arm", "disarm", "armed", "reset", "on",
    "fail_point", "survived", "stats", "scenario",
    "arm_from_spec", "make_spec",
    "RAISE", "KILL", "sleep", "truncate",
    "scenarios",
]

from . import scenarios  # noqa: E402  (uses the core surface above)
