"""Composed chaos scenarios -- the experiments tests, CI's ``chaos``
stage, and the hot-swap bench share.

Each scenario is deterministic for a fixed seed, runs on CPU in a few
seconds, and returns a plain report dict the caller gates on; the
assertions live with the callers (tests/test_chaos.py, ci/run_all.sh)
so CI failures name the violated contract, not just "scenario failed".
"""
from __future__ import annotations

import os
import threading
import time

import numpy as np

from . import core as chaos

__all__ = ["make_mlp", "train_fixtures", "corrupt_dirs",
           "hotswap_scenario", "flood_scenario"]


def make_mlp(in_dim=8, hidden=16, out=4):
    """A tiny deterministic MLP (the scenario workhorse: compiles in
    milliseconds on CPU, params small enough to checkpoint per step)."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(hidden, activation="relu"),
            gluon.nn.Dense(out))
    net.initialize(force_reinit=True)
    net.hybridize()
    net(mx.nd.array(np.zeros((1, in_dim), np.float32)))
    return net


def train_fixtures(seed=0, in_dim=8, out=4, batch=8):
    """(net, trainer, loss_fn, (x, y)) for a ContinuousTrainer."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    net = make_mlp(in_dim=in_dim, out=out)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore=None)
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(seed)
    x = mx.nd.array(rng.rand(batch, in_dim).astype(np.float32))
    y = mx.nd.array(rng.rand(batch, out).astype(np.float32))
    return net, trainer, loss_fn, (x, y)


def corrupt_dirs(root):
    """The ``step_*.corrupt`` quarantine dirs under a checkpoint root."""
    try:
        return sorted(d for d in os.listdir(root)
                      if d.endswith(".corrupt"))
    except OSError:
        return []


def hotswap_scenario(root, torn=False, seed=0, clients=3,
                     requests_per_client=20, publish_every=2,
                     buckets=(1, 2, 4), max_wait_ms=2.0,
                     request_timeout=30.0):
    """Continuous-train -> hot-swap under concurrent client load.

    Phase 1 trains and publishes step ``publish_every``; the watcher
    swaps it in.  Client threads then hammer ``registry.infer``
    throughout phase 2, which trains and publishes step
    ``2 * publish_every`` -- torn mid-commit by an armed chaos rule
    when ``torn=True`` (the kill-mid-commit disk state) -- and the
    watcher polls again.

    Report keys: ``served_step`` (the rollback proof: stays at the
    first step when the newer one is torn), ``published_step``,
    ``quarantined`` (the ``*.corrupt`` renames), ``completed`` /
    ``shed`` / ``errors`` per-request outcomes (the zero-dropped
    proof), ``swap_hits`` (fail-point visits), and ``chaos`` stats.
    """
    from mxnet_tpu import serving
    from mxnet_tpu.serving.loop import ContinuousTrainer, RegistryWatcher

    net, trainer, loss_fn, data = train_fixtures(seed=seed)
    mgr_root = os.fspath(root)
    ct = ContinuousTrainer(net, trainer, loss_fn, data, mgr_root,
                           publish_every=publish_every)
    reg = serving.ModelRegistry(compile_cache=False)
    watcher = RegistryWatcher(reg, "model", ct.manager, make_mlp(),
                              input_shape=(8,), poll_s=0.05,
                              swap_retries=0, buckets=buckets,
                              max_wait_ms=max_wait_ms, max_queue=256)
    outcomes = {"completed": 0, "shed": 0, "errors": [],
                "completed_after_swap": 0}
    outcomes_lock = threading.Lock()
    sample = np.random.RandomState(seed).rand(8).astype(np.float32)
    start_gate = threading.Event()
    stop_clients = threading.Event()
    swap_done = threading.Event()

    def client():
        start_gate.wait(10)
        sent = 0
        # minimum requests_per_client requests, then keep the load on
        # until the swap window has closed -- so requests provably
        # overlap the drain-then-replace
        while sent < requests_per_client or not stop_clients.is_set():
            sent += 1
            try:
                reg.infer("model", sample, timeout=request_timeout)
            except serving.ServingQueueFull:
                with outcomes_lock:
                    outcomes["shed"] += 1
                continue
            except Exception as e:
                with outcomes_lock:
                    outcomes["errors"].append(type(e).__name__)
                continue
            with outcomes_lock:
                outcomes["completed"] += 1
                if swap_done.is_set():
                    outcomes["completed_after_swap"] += 1
            time.sleep(0.002)  # mxlint: disable=sleep-poll

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(clients)]
    report = {}
    with chaos.scenario(seed=seed):
        if torn:
            # the second publish tears right after its atomic commit --
            # the bytes a SIGKILL'd non-atomic writer would leave
            chaos.on("checkpoint.commit.post_commit", nth=2,
                     action=chaos.truncate("params.params"))
        ct.run_steps(publish_every)           # publish step N (intact)
        first = watcher.poll_once()
        for t in threads:
            t.start()
        start_gate.set()
        ct.run_steps(publish_every)           # publish step 2N
        import warnings as _warnings
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore", RuntimeWarning)
            second = watcher.poll_once()      # torn => quarantine+hold
        swap_done.set()
        time.sleep(0.1)      # a post-swap request window for every client
        stop_clients.set()
        for t in threads:
            t.join()
        report["chaos"] = chaos.stats()
    ct.close()
    watcher.close()
    reg.shutdown(drain=True)
    report.update(outcomes)
    report.update({
        "first_swap_step": first,
        "second_swap_step": second,
        "served_step": watcher.served_step,
        "published_step": ct.published_step,
        "quarantined": corrupt_dirs(mgr_root),
        "requests": outcomes["completed"] + outcomes["shed"]
        + len(outcomes["errors"]),
    })
    return report


def flood_scenario(seed=0, max_queue=4, clients=8, per_client=8,
                   hold_s=0.03, request_timeout=30.0):
    """Flood the dynamic batcher past ``MXNET_TPU_SERVING_QUEUE``.

    A chaos rule stalls every compiled dispatch by ``hold_s`` (the
    wedged-device weather), ``clients`` threads release together and
    submit ``per_client`` requests each with no pacing against a
    single-slot bucket and a ``max_queue``-deep queue -- so intake
    outruns service and the bounded queue MUST shed.

    The contracts the report proves: sheds raise the distinct
    ``ServingQueueFull`` (counted), every *accepted* request still
    completes (``completed + shed == requests``, no other errors), and
    the max completed latency stays bounded by the queue depth times
    the injected stall -- p99 cannot grow past the bound the queue
    exists to enforce.
    """
    from mxnet_tpu import serving, telemetry

    net = make_mlp()
    reg = serving.ModelRegistry(compile_cache=False)
    shed_before = telemetry.counter("serving.shed").value \
        if telemetry.enabled() else None
    outcomes = {"completed": 0, "shed": 0, "errors": []}
    outcomes_lock = threading.Lock()
    latencies = []
    sample = np.random.RandomState(seed).rand(8).astype(np.float32)
    barrier = threading.Barrier(clients)

    def client():
        barrier.wait(10)
        for _ in range(per_client):
            t0 = time.perf_counter()
            try:
                reg.infer("flood", sample, timeout=request_timeout)
            except serving.ServingQueueFull:
                with outcomes_lock:
                    outcomes["shed"] += 1
                continue
            except Exception as e:
                with outcomes_lock:
                    outcomes["errors"].append(type(e).__name__)
                continue
            with outcomes_lock:
                outcomes["completed"] += 1
                latencies.append(time.perf_counter() - t0)

    report = {}
    with chaos.scenario(seed=seed):
        chaos.on("serving.dispatch", action=chaos.sleep(hold_s))
        reg.register("flood", block=net, input_shape=(8,),
                     buckets=(1,), max_wait_ms=1.0,
                     max_queue=max_queue)
        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        report["chaos"] = chaos.stats()
    reg.shutdown(drain=True)
    report.update(outcomes)
    lat = sorted(latencies)
    report.update({
        "requests": clients * per_client,
        "max_queue": max_queue,
        "hold_s": hold_s,
        "max_latency_s": lat[-1] if lat else None,
        "p99_latency_s": lat[min(len(lat) - 1, int(0.99 * len(lat)))]
        if lat else None,
        # worst admissible wait: a full queue ahead of you plus your
        # own dispatch, each stalled hold_s (+1 slack for the in-flight
        # batch and scheduler jitter)
        "latency_bound_s": (max_queue + 2) * hold_s + 1.0,
        "shed_counter_delta":
        (telemetry.counter("serving.shed").value - shed_before)
        if shed_before is not None else None,
    })
    return report
