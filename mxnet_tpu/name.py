"""Name manager (reference: ``python/mxnet/name.py``): exposes the
shared auto-naming scope as the public ``mx.name`` surface."""
from .base import _NameManager as NameManager


class Prefix(NameManager):
    """Prepend a prefix to every auto name (reference: ``Prefix``)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)
