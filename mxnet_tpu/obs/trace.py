"""Context-propagated trace/span IDs: the Dapper-style causality layer
(ISSUE 13 tentpole).

``mx.telemetry`` answers *how much* (counters, histograms); this module
answers *which request* and *in what order*: every unit of work carries
a ``TraceContext`` (trace id + span id) in a ``contextvars.ContextVar``,
child spans record their parent, and cross-thread fan-in (the serving
batcher assembling many requests into one compiled dispatch) is modeled
as **span links** -- the batch span names every request span it serves,
exactly the Dapper/OpenTelemetry shape.

Two recording surfaces:

- :func:`span` / :func:`trace` -- context managers for code that OWNS
  its scope (user code, tests);
- :func:`begin_span` / :func:`end_span` and :func:`record_span` -- the
  hook surface the instrumented framework paths use, so a disabled
  tracer costs exactly one module-flag check per site
  (``obs._TRACE_ENABLED``, the same zero-overhead contract as
  ``telemetry._ENABLED``, proven by tests/test_obs.py).

Every finished span lands in (1) a bounded in-process ring (the flight
recorder and :func:`export_chrome_trace` read it), (2) the attached
telemetry sinks as a streamed ``{"kind": "span", ...}`` JSONL record
(``mxtelemetry summarize`` folds them), and (3) the profiling timeline
ring when ``mx.profiling`` is enabled, so traces overlay the existing
Chrome-trace step timeline.
"""
from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
import uuid

from .. import sync as _sync

__all__ = [
    "TraceContext", "current", "new_id", "trace", "span",
    "begin_span", "end_span", "record_span", "spans", "clear",
    "export_chrome_trace",
]

# bounded span ring: a multi-hour run must not grow host memory
_MAX_SPANS = 16_384

_CTX = contextvars.ContextVar("mxtpu_trace", default=None)
_lock = _sync.Lock(name="obs.spans")
_spans = []
_dropped = 0


class TraceContext:
    """One (trace_id, span_id) position in a trace tree."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id

    def child(self):
        """A fresh span position under the same trace."""
        return TraceContext(self.trace_id, new_id())

    def __repr__(self):
        return "TraceContext(trace=%s, span=%s)" % (self.trace_id,
                                                    self.span_id)


def new_id():
    """16-hex-char random id (uuid4-derived; no global RNG state)."""
    return uuid.uuid4().hex[:16]


def current():
    """The active TraceContext of this thread/task, or None."""
    return _CTX.get()


def fresh_context():
    """Current context if one is active, else a brand-new root trace --
    what a request boundary (serving submit) uses so externally-traced
    and untraced clients both get causality."""
    ctx = _CTX.get()
    if ctx is not None:
        return TraceContext(ctx.trace_id, new_id())
    return TraceContext(new_id(), new_id())


class _OpenSpan:
    __slots__ = ("name", "ctx", "parent_id", "t0", "t_wall", "attrs",
                 "token")

    def __init__(self, name, ctx, parent_id, attrs, token):
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.t0 = time.perf_counter()
        self.t_wall = time.time()
        self.attrs = attrs
        self.token = token


def begin_span(name, **attrs):
    """Open a span as a child of the current context and make it the
    current context.  Returns the open-span token for :func:`end_span`.
    The framework hook surface: call sites guard with
    ``if _obs._TRACE_ENABLED`` so the disabled cost is one flag check."""
    parent = _CTX.get()
    if parent is not None:
        ctx = parent.child()
        parent_id = parent.span_id
    else:
        ctx = TraceContext(new_id(), new_id())
        parent_id = None
    token = _CTX.set(ctx)
    return _OpenSpan(name, ctx, parent_id, attrs or None, token)


def end_span(open_span, **extra_attrs):
    """Close a span opened by :func:`begin_span`: restore the previous
    context and record the finished span."""
    _CTX.reset(open_span.token)
    attrs = open_span.attrs
    if extra_attrs:
        attrs = dict(attrs or {}, **extra_attrs)
    record_span(open_span.name, open_span.ctx,
                parent_id=open_span.parent_id,
                t0=open_span.t0,
                dur=time.perf_counter() - open_span.t0,
                t_wall=open_span.t_wall, attrs=attrs)
    return open_span.ctx


@contextlib.contextmanager
def span(name, **attrs):
    """``with obs.span("phase"): ...`` -- scoped child span."""
    sp = begin_span(name, **attrs)
    try:
        yield sp.ctx
    finally:
        end_span(sp)


@contextlib.contextmanager
def trace(name="trace", trace_id=None, **attrs):
    """Open a new root trace (or adopt ``trace_id``) for the enclosed
    block.  The root span records on exit like any other."""
    ctx = TraceContext(trace_id or new_id(), new_id())
    token = _CTX.set(ctx)
    t0 = time.perf_counter()
    t_wall = time.time()
    try:
        yield ctx
    finally:
        _CTX.reset(token)
        record_span(name, ctx, parent_id=None, t0=t0,
                    dur=time.perf_counter() - t0, t_wall=t_wall,
                    attrs=attrs or None)


def record_span(name, ctx, parent_id=None, t0=None, dur=0.0,
                t_wall=None, attrs=None, links=None):
    """Record one finished span with explicit timing -- the surface for
    cross-thread spans whose begin and end live on different threads
    (queue wait measured by the batcher worker from the submit mark).

    ``t0`` is on the perf_counter clock (Chrome-trace placement);
    ``t_wall`` is wall time (JSONL ``t`` field, cross-process merge).
    ``links`` carries span ids this span serves but is not a child of
    (batcher fan-in).
    """
    global _dropped
    rec = {
        "kind": "span",
        "name": name,
        "trace": ctx.trace_id,
        "span": ctx.span_id,
        "parent": parent_id,
        "t": t_wall if t_wall is not None else time.time(),
        "t0": t0 if t0 is not None else time.perf_counter(),
        "dur": float(dur),
    }
    if attrs:
        rec["attrs"] = attrs
    if links:
        rec["links"] = list(links)
    with _lock:
        if len(_spans) >= _MAX_SPANS:
            del _spans[:_MAX_SPANS // 10]
            _dropped += _MAX_SPANS // 10
        _spans.append(rec)
    # stream to the attached telemetry sinks (JSONL run log, flight
    # recorder); Registry._stream is sink fan-out only -- it does not
    # require telemetry to be enabled, so tracing stands alone
    from .. import telemetry as _telemetry
    _telemetry.registry()._stream(rec)
    # overlay on the profiling step timeline when cost accounting is on
    from .. import profiling as _profiling
    if _profiling.enabled():
        from ..profiling import timeline as _timeline
        _timeline.record(name, rec["t0"], rec["dur"],
                         args={"trace": ctx.trace_id,
                               "span": ctx.span_id})
    return rec


def spans():
    """Snapshot of the bounded span ring (oldest first)."""
    with _lock:
        return list(_spans)


def dropped():
    return _dropped


def clear():
    global _dropped
    with _lock:
        del _spans[:]
        _dropped = 0


def export_chrome_trace(path=None):
    """Chrome trace-event JSON of the span ring: complete ('X') events
    with trace/span/parent ids in ``args``, loadable in Perfetto or
    chrome://tracing.  Written to ``path`` when given; the dict is
    returned either way."""
    import json
    evs = []
    for rec in spans():
        args = {"trace": rec["trace"], "span": rec["span"]}
        if rec.get("parent"):
            args["parent"] = rec["parent"]
        if rec.get("links"):
            args["links"] = rec["links"]
        if rec.get("attrs"):
            args.update(rec["attrs"])
        evs.append({"name": rec["name"], "ph": "X",
                    "ts": rec["t0"] * 1e6, "dur": rec["dur"] * 1e6,
                    "pid": os.getpid(), "tid": threading.get_ident(),
                    "args": args})
    doc = {"traceEvents": evs, "displayTimeUnit": "ms",
           "otherData": {"producer": "mxnet_tpu.obs.trace",
                         "dropped_spans": _dropped}}
    if path:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc
