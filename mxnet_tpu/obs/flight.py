"""Crash-safe flight recorder: the black box an operator reads AFTER
the process died (ISSUE 13 tentpole).

A bounded circular byte ring of the most recent telemetry records
(event emits, timer samples, spans) lives in an **mmap'd file**:
every write lands in the page cache immediately, so the bytes survive
``os._exit`` (the chaos KILL), SIGKILL at grace-window expiry, and any
Python-level crash -- no atexit, no flush() discipline required.  Only
losing the whole machine loses the ring.

Layout: a fixed 32-byte header (magic, data capacity, write position,
total bytes ever written) followed by ``capacity`` data bytes holding
newline-delimited JSON records written circularly.  The file is
*created* through the checkpoint subsystem's atomic
:func:`~mxnet_tpu.checkpoint.core.commit` helper, so a reader can never
observe a half-initialized ring; after creation all writes go through
the mmap.  A record torn by a crash between the payload write and the
header update parses as garbage on exactly one line and is skipped by
:func:`read` -- the same corruption-tolerance posture as checkpoint
discovery.

The recorder attaches to the telemetry registry as a sink (it receives
every streamed record) and is dumped -- final marker event + msync --
automatically from three death paths:

- the **preemption handler** (SIGTERM landed);
- the **chaos KILL** action (``os._exit(137)`` mid-fault-injection);
- a ``faulthandler``-style **SIGUSR2** hook that snapshots every
  thread's stack into the ring on demand (wedged-process postmortem
  without killing it).

Render with ``mxtelemetry blackbox <file>``.
"""
from __future__ import annotations

import json
import mmap
import os
import signal
import struct
import sys
import threading
import time
import traceback

from .. import sync as _sync
from ..base import MXNetError

__all__ = ["FlightRecorder", "install", "installed", "uninstall",
           "note", "emergency_dump", "read", "DEFAULT_CAPACITY"]

_MAGIC = b"MXBBOX1\n"
# header: magic(8s) capacity(Q) write_pos(Q) total_written(Q)
_HEADER = struct.Struct("<8sQQQ")
HEADER_SIZE = _HEADER.size          # 32
DEFAULT_CAPACITY = 256 * 1024


def _json_default(obj):
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(obj)


class FlightRecorder:
    """One process's bounded crash-surviving record ring.

    Implements the telemetry sink protocol (``write(record)``), so
    attaching it to the registry makes every streamed event/sample/span
    part of the post-mortem record.
    """

    def __init__(self, path, capacity=None):
        if capacity is None:
            from .. import env as _env
            capacity = int(_env.get("MXNET_TPU_OBS_BLACKBOX_KB")) * 1024
        if capacity < 4096:
            raise MXNetError("flight recorder capacity %d too small "
                             "(min 4096 bytes)" % capacity)
        self.path = os.fspath(path)
        self.capacity = int(capacity)
        self._lock = _sync.Lock(name="obs.flight")
        self._closed = False
        # atomic creation: a fresh zeroed ring + header lands via the
        # checkpoint commit helper, so no reader ever sees a torn file
        from ..checkpoint import core as _ckpt

        def _init(tmp):
            with open(tmp, "wb") as f:
                f.write(_HEADER.pack(_MAGIC, self.capacity, 0, 0))
                f.truncate(HEADER_SIZE + self.capacity)
        _ckpt.commit(self.path, _init)
        self._f = open(self.path, "r+b")
        self._mm = mmap.mmap(self._f.fileno(),
                             HEADER_SIZE + self.capacity)
        self._pos = 0
        self._total = 0

    # -- sink protocol --------------------------------------------------
    def write(self, record):
        """Append one telemetry record (dict) to the ring."""
        try:
            line = json.dumps(record, default=_json_default)
        except Exception:
            return
        self._append((line + "\n").encode("utf-8", "replace"))

    def flush(self):
        self.sync()

    # -- direct notes ---------------------------------------------------
    def note(self, name, **payload):
        """Record an operator-facing marker event directly (bypasses
        telemetry entirely -- death paths must record even in a run
        that never enabled instrumentation)."""
        self.write({"kind": "event", "name": name, "t": time.time(),
                    "payload": payload})

    # -- ring mechanics -------------------------------------------------
    def _append(self, data):
        if len(data) > self.capacity:
            data = data[-self.capacity:]
        with self._lock:
            if self._closed:
                return
            pos = self._pos
            end = pos + len(data)
            if end <= self.capacity:
                self._mm[HEADER_SIZE + pos:HEADER_SIZE + end] = data
            else:
                head = self.capacity - pos
                self._mm[HEADER_SIZE + pos:HEADER_SIZE
                         + self.capacity] = data[:head]
                self._mm[HEADER_SIZE:HEADER_SIZE
                         + (end - self.capacity)] = data[head:]
            self._pos = end % self.capacity
            self._total += len(data)
            # header LAST: a crash mid-payload leaves the previous
            # header, and the overwritten bytes read as one torn line
            _HEADER.pack_into(self._mm, 0, _MAGIC, self.capacity,
                              self._pos, self._total)

    def sync(self):
        """msync the ring to storage (belt-and-braces: the page cache
        already survives process death; this survives power loss of
        everything but the disk)."""
        with self._lock:
            if not self._closed:
                self._mm.flush()

    def records(self):
        """Parse this recorder's own ring (tests/introspection)."""
        self.sync()
        return read(self.path)

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._mm.flush()
            self._mm.close()
            self._f.close()


def read(path):
    """Parse a flight-recorder file into its records, oldest first.
    Torn/partial lines (crash mid-write, ring wrap) are skipped, not
    fatal.  Raises OSError when the file is missing, MXNetError when it
    is not a flight-recorder ring."""
    with open(path, "rb") as f:
        header = f.read(HEADER_SIZE)
        if len(header) < HEADER_SIZE:
            raise MXNetError("%s: not a flight recorder (short header)"
                             % path)
        magic, capacity, pos, total = _HEADER.unpack(header)
        if magic != _MAGIC:
            raise MXNetError("%s: not a flight recorder (bad magic)"
                             % path)
        data = f.read(capacity)
    if total <= capacity:
        raw = data[:pos]
        wrapped = False
    else:
        raw = data[pos:] + data[:pos]
        wrapped = True
    out = []
    for i, line in enumerate(raw.split(b"\n")):
        if not line:
            continue
        if i == 0 and wrapped:
            # the oldest surviving line was half-overwritten by the
            # newest write; its head bytes are gone by construction
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


# ----------------------------------------------------------------------
# process-global install + death-path dumps
# ----------------------------------------------------------------------

_recorder = None
_prev_usr2 = None


def install(path=None, capacity=None, sigusr2=True):
    """Create the process flight recorder, attach it to the telemetry
    registry as a sink, and arm the SIGUSR2 stack-dump hook.  ``path``
    defaults to ``MXNET_TPU_OBS_BLACKBOX``.  Returns the recorder."""
    global _recorder, _prev_usr2
    if path is None:
        from .. import env as _env
        path = _env.get("MXNET_TPU_OBS_BLACKBOX")
        if not path:
            raise MXNetError("obs.flight.install: no path given and "
                             "MXNET_TPU_OBS_BLACKBOX is unset")
    uninstall()
    rec = FlightRecorder(path, capacity=capacity)
    from .. import telemetry as _telemetry
    _telemetry.registry().attach(rec)
    rec.note("obs.blackbox.open", pid=os.getpid(),
             argv=" ".join(sys.argv[:4]))
    if sigusr2:
        try:
            _prev_usr2 = signal.signal(signal.SIGUSR2, _on_sigusr2)
        except ValueError:
            _prev_usr2 = None   # not the main thread; hook unavailable
    _recorder = rec
    return rec


def installed():
    """The process flight recorder, or None."""
    return _recorder


def uninstall():
    """Detach and close the process recorder (tests)."""
    global _recorder, _prev_usr2
    rec, _recorder = _recorder, None
    if rec is None:
        return
    from .. import telemetry as _telemetry
    _telemetry.registry().detach(rec)
    rec.close()
    if _prev_usr2 is not None:
        try:
            signal.signal(signal.SIGUSR2, _prev_usr2)
        except ValueError:
            pass
        _prev_usr2 = None


def note(name, **payload):
    """Marker into the process recorder, if one is installed (the
    guarded one-liner the death paths call)."""
    rec = _recorder
    if rec is not None:
        rec.note(name, **payload)


def emergency_dump(reason, **payload):
    """The death-path dump: record the reason (with the in-flight trace
    context, so a postmortem names WHICH request/step died), msync, and
    never raise -- callable from a signal handler or the instruction
    before ``os._exit``."""
    rec = _recorder
    if rec is None:
        return False
    try:
        from . import trace as _trace
        ctx = _trace.current()
        if ctx is not None:
            payload.setdefault("trace", ctx.trace_id)
            payload.setdefault("span", ctx.span_id)
        rec.note(reason, **payload)
        rec.sync()
    except Exception:
        pass
    return True


def _thread_stacks():
    """One formatted stack per live thread (faulthandler-shaped, but
    JSON-serializable)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in sys._current_frames().items():
        label = "%s(%d)" % (names.get(ident, "?"), ident)
        stacks[label] = "".join(traceback.format_stack(frame))[-4000:]
    return stacks


def _on_sigusr2(signum, frame):
    """faulthandler-style on-demand postmortem of a LIVE process: every
    thread's stack lands in the ring, then msync.  Re-raises nothing;
    chains to any previous handler."""
    emergency_dump("obs.sigusr2", stacks=_thread_stacks())
    prev = _prev_usr2
    if callable(prev):
        prev(signum, frame)
